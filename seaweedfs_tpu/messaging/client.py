"""Messaging client library (reference `messaging/msgclient/`): publisher
with consistent-hash partition→broker routing, poll-based subscriber."""

from __future__ import annotations

import base64
import time
from typing import Iterator, Optional

from ..server.http_util import http_bytes, http_json
from .consistent import ConsistentRing


class MessagingClient:
    def __init__(self, brokers: list[str]):
        self.brokers = brokers
        self.ring = ConsistentRing()
        for b in brokers:
            self.ring.add(b)

    def _broker_for(self, ns: str, topic: str, partition: int) -> str:
        return self.ring.get(f"{ns}/{topic}/{partition:02d}")

    # -- topic admin ---------------------------------------------------------
    def create_topic(self, ns: str, topic: str, partitions: int = 4) -> dict:
        # every broker: creation clears any delete-tombstone a broker holds
        # for this topic (deletes fan out the same way)
        out = {}
        for b in self.brokers:
            out = http_json(
                "POST",
                f"http://{b}/topics/{ns}/{topic}?partitions={partitions}",
            )
        return out

    def topic_conf(self, ns: str, topic: str) -> dict:
        return http_json("GET", f"http://{self.brokers[0]}/topics/{ns}/{topic}")

    def delete_topic(self, ns: str, topic: str) -> dict:
        """DeleteTopic (messaging.proto): drop log tree + conf everywhere —
        every broker may hold live partitions of it."""
        out = {}
        for b in self.brokers:
            out = http_json(
                "POST", f"http://{b}/topics/{ns}/{topic}?op=delete"
            )
        return out

    # -- publish -------------------------------------------------------------
    def publish(
        self,
        ns: str,
        topic: str,
        value: bytes,
        key: bytes = b"",
        partition: Optional[int] = None,
    ) -> int:
        conf = self.topic_conf(ns, topic)
        n = conf.get("partitions", 1)
        if partition is None:
            partition = (hash(key) if key else time.monotonic_ns()) % n
        broker = self._broker_for(ns, topic, partition)
        import urllib.request

        req = urllib.request.Request(
            f"http://{broker}/pub/{ns}/{topic}/{partition}",
            data=value,
            method="POST",
        )
        if key:
            req.add_header("X-Msg-Key", base64.b64encode(key).decode())
        with urllib.request.urlopen(req, timeout=30) as resp:
            import json

            return json.loads(resp.read())["ts_ns"]

    # -- subscribe -----------------------------------------------------------
    def fetch(
        self, ns: str, topic: str, partition: int, since_ns: int = 0,
        limit: int = 1000,
    ) -> tuple[list[dict], int]:
        broker = self._broker_for(ns, topic, partition)
        status, body = http_bytes(
            "GET",
            f"http://{broker}/sub/{ns}/{topic}/{partition}"
            f"?since_ns={since_ns}&limit={limit}",
        )
        import json

        d = json.loads(body)
        msgs = [
            {
                "ts_ns": m["ts_ns"],
                "key": base64.b64decode(m["key"]),
                "value": base64.b64decode(m["value"]),
            }
            for m in d.get("messages", [])
        ]
        return msgs, d.get("last_ts_ns", since_ns)

    def subscribe(
        self,
        ns: str,
        topic: str,
        partition: int,
        since_ns: int = 0,
        poll_interval: float = 0.1,
        stop_after_idle: Optional[float] = None,
    ) -> Iterator[dict]:
        """Replay from since_ns then tail. Yields message dicts; stops after
        `stop_after_idle` seconds without new messages (None = forever)."""
        offset = since_ns
        idle_since = time.monotonic()
        while True:
            msgs, offset = self.fetch(ns, topic, partition, offset)
            if msgs:
                idle_since = time.monotonic()
                yield from msgs
            else:
                if (
                    stop_after_idle is not None
                    and time.monotonic() - idle_since > stop_after_idle
                ):
                    return
                time.sleep(poll_interval)
