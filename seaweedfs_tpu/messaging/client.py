"""Messaging client library (reference `messaging/msgclient/`): publisher
with consistent-hash partition→broker routing, poll-based subscriber, and
the channel layer (`chan_pub.go:15` PubChannel / `chan_sub.go:16`
SubChannel) — named one-partition streams under the reserved "chan"
namespace with md5 integrity accumulators and an in-band close marker."""

from __future__ import annotations

import base64
import hashlib
import time
from typing import Iterator, Optional

from ..server.http_util import http_bytes, http_json
from .consistent import ConsistentRing
from .consistent import _hash as _ring_hash

def partition_for_key(key: bytes, partitions: int) -> int:
    """Stable key→partition routing, NOT Python hash(): per-key ordering
    only holds if every producer process (and every restart — hash(bytes)
    is salted per-interpreter via PYTHONHASHSEED) routes the same key to
    the same partition. Shares the ring's digest (consistent._hash)."""
    return _ring_hash(key) % partitions


# the reference marks end-of-channel with Message.IsClose (chan_pub.go:55);
# this wire carries key+value, so a reserved key is the close marker — keys
# beginning with NUL are not constructible through the channel Publish API
_CHAN_NS = "chan"
_CLOSE_KEY = b"\x00chan.close"


class MessagingClient:
    def __init__(self, brokers: list[str]):
        self.brokers = brokers
        self.ring = ConsistentRing()
        for b in brokers:
            self.ring.add(b)

    def _broker_for(self, ns: str, topic: str, partition: int) -> str:
        return self.ring.get(f"{ns}/{topic}/{partition:02d}")

    # -- topic admin ---------------------------------------------------------
    def create_topic(self, ns: str, topic: str, partitions: int = 4) -> dict:
        # every broker: creation clears any delete-tombstone a broker holds
        # for this topic (deletes fan out the same way)
        out = {}
        for b in self.brokers:
            out = http_json(
                "POST",
                f"http://{b}/topics/{ns}/{topic}?partitions={partitions}",
            )
        return out

    def topic_conf(self, ns: str, topic: str) -> dict:
        return http_json("GET", f"http://{self.brokers[0]}/topics/{ns}/{topic}")

    def delete_topic(self, ns: str, topic: str) -> dict:
        """DeleteTopic (messaging.proto): drop log tree + conf everywhere —
        every broker may hold live partitions of it."""
        out = {}
        for b in self.brokers:
            out = http_json(
                "POST", f"http://{b}/topics/{ns}/{topic}?op=delete"
            )
        return out

    # -- publish -------------------------------------------------------------
    def publish(
        self,
        ns: str,
        topic: str,
        value: bytes,
        key: bytes = b"",
        partition: Optional[int] = None,
    ) -> int:
        if partition is None:
            conf = self.topic_conf(ns, topic)
            n = conf.get("partitions", 1)
            partition = (
                partition_for_key(key, n) if key
                else time.monotonic_ns() % n
            )
        broker = self._broker_for(ns, topic, partition)
        import urllib.request

        req = urllib.request.Request(
            f"http://{broker}/pub/{ns}/{topic}/{partition}",
            data=value,
            method="POST",
        )
        if key:
            req.add_header("X-Msg-Key", base64.b64encode(key).decode())
        # sweedlint: ok deadline-not-propagated broker pub is fire-and-forget from producers, not a fan of an inbound request; its own timeout bounds it
        with urllib.request.urlopen(req, timeout=30) as resp:
            import json

            return json.loads(resp.read())["ts_ns"]

    # -- subscribe -----------------------------------------------------------
    def fetch(
        self, ns: str, topic: str, partition: int, since_ns: int = 0,
        limit: int = 1000,
    ) -> tuple[list[dict], int]:
        broker = self._broker_for(ns, topic, partition)
        status, body = http_bytes(
            "GET",
            f"http://{broker}/sub/{ns}/{topic}/{partition}"
            f"?since_ns={since_ns}&limit={limit}",
        )
        import json

        d = json.loads(body)
        msgs = [
            {
                "ts_ns": m["ts_ns"],
                "key": base64.b64decode(m["key"]),
                "value": base64.b64decode(m["value"]),
            }
            for m in d.get("messages", [])
        ]
        return msgs, d.get("last_ts_ns", since_ns)

    # -- channels (msgclient/chan_pub.go, chan_sub.go) -----------------------
    def new_pub_channel(self, chan_name: str) -> "PubChannel":
        """NewPubChannel (chan_pub.go:21): a named single-partition stream
        under the reserved "chan" namespace."""
        self.create_topic(_CHAN_NS, chan_name, partitions=1)
        return PubChannel(self, chan_name)

    def new_sub_channel(self, subscriber_id: str, chan_name: str) -> "SubChannel":
        """NewSubChannel (chan_sub.go:23). `subscriber_id` names the
        consumer for diagnostics (the poll transport needs no server-side
        registration)."""
        self.create_topic(_CHAN_NS, chan_name, partitions=1)
        return SubChannel(self, subscriber_id, chan_name)

    def subscribe(
        self,
        ns: str,
        topic: str,
        partition: int,
        since_ns: int = 0,
        poll_interval: float = 0.1,
        stop_after_idle: Optional[float] = None,
    ) -> Iterator[dict]:
        """Replay from since_ns then tail. Yields message dicts; stops after
        `stop_after_idle` seconds without new messages (None = forever)."""
        offset = since_ns
        idle_since = time.monotonic()
        while True:
            msgs, offset = self.fetch(ns, topic, partition, offset)
            if msgs:
                idle_since = time.monotonic()
                yield from msgs
            else:
                if (
                    stop_after_idle is not None
                    and time.monotonic() - idle_since > stop_after_idle
                ):
                    return
                time.sleep(poll_interval)


class PubChannel:
    """Write side of a named channel (chan_pub.go:15): every Publish lands
    on partition 0 of chan/<name>, an md5 accumulates over published bytes
    (the reference's transfer-integrity check), and close() sends the
    in-band close marker that ends the far side's iteration."""

    def __init__(self, mc: MessagingClient, name: str):
        self._mc = mc
        self.name = name
        self._md5 = hashlib.md5()
        self._closed = False

    def publish(self, value: bytes) -> int:
        if self._closed:
            raise ValueError(f"channel {self.name} is closed")
        ts = self._mc.publish(_CHAN_NS, self.name, value, partition=0)
        self._md5.update(value)
        return ts

    def close(self) -> None:
        if not self._closed:
            # only latch closed once the marker is durably published — a
            # failed close() must stay retryable or subscribers hang forever
            self._mc.publish(
                _CHAN_NS, self.name, b"", key=_CLOSE_KEY, partition=0
            )
            self._closed = True

    def md5(self) -> bytes:
        return self._md5.digest()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SubChannel:
    """Read side (chan_sub.go:16): iterates values from the beginning of
    the channel, ends cleanly at the close marker, and accumulates the
    same md5 so both ends can compare digests after the stream."""

    def __init__(self, mc: MessagingClient, subscriber_id: str, name: str):
        self._mc = mc
        self.subscriber_id = subscriber_id
        self.name = name
        self._md5 = hashlib.md5()

    def __iter__(self) -> Iterator[bytes]:
        for m in self._mc.subscribe(_CHAN_NS, self.name, 0, since_ns=0):
            if m["key"] == _CLOSE_KEY:
                return
            self._md5.update(m["value"])
            yield m["value"]

    def md5(self) -> bytes:
        return self._md5.digest()
