"""Message broker daemon (reference `messaging/broker/broker_server.go` +
`topic_manager.go`): per-(topic,partition) log buffers, segments persisted
as filer files under `/topics/<ns>/<topic>/<partition>/`, subscribe replays
persisted segments then tails memory (`broker_grpc_server_subscribe.go:18,137`).
"""

from __future__ import annotations

import base64
import threading
from typing import Optional

from ..filer.client import FilerClient
from ..server.http_util import JsonHandler, start_server
from ..util.parsers import tolerant_uint
from .log_buffer import LogBuffer, decode_messages
from ..util.locks import make_lock

TOPICS_ROOT = "/topics"


class TopicPartition:
    def __init__(self, client: FilerClient, ns: str, topic: str, partition: int):
        self.client = client
        self.dir = f"{TOPICS_ROOT}/{ns}/{topic}/{partition:02d}"
        self.buffer = LogBuffer(
            flush_fn=self._flush_segment,
            flush_bytes=1 * 1024 * 1024,
            flush_interval=1.0,
        )

    def _flush_segment(self, start_ts: int, stop_ts: int, blob: bytes) -> None:
        # segment name = zero-padded start ts → names sort chronologically
        self.client.put_object(f"{self.dir}/{start_ts:020d}.seg", blob)

    def publish(self, key: bytes, value: bytes) -> int:
        return self.buffer.append(key, value)

    def read(self, since_ns: int, limit: int = 1000):
        """Persisted segments for history, memory for the tail; strictly
        increasing ts guarantees the overlap dedupes itself."""
        out = []
        floor = self.buffer.memory_floor_ts()
        if since_ns + 1 < floor or floor == 0:
            segs = [
                e["name"]
                for e in self.client.list(self.dir, limit=100000)
                if e["name"].endswith(".seg")
            ]
            segs.sort()
            # a segment may span since_ns, so include the newest one starting
            # at or before it, plus everything after
            keep, last_before = [], None
            for name in segs:
                if int(name.split(".")[0]) > since_ns:
                    keep.append(name)
                else:
                    last_before = name
            if last_before is not None:
                keep.insert(0, last_before)
            for name in keep:
                status, blob, _ = self.client.get_object(f"{self.dir}/{name}")
                if status != 200:
                    continue
                for ts, k, v in decode_messages(blob):
                    if ts > since_ns and (floor == 0 or ts < floor):
                        out.append((ts, k, v))
                        if len(out) >= limit:
                            return out
        last = out[-1][0] if out else since_ns
        out.extend(self.buffer.read_since(last, limit - len(out)))
        return out[:limit]

    def close(self):
        self.buffer.close()

    def discard(self):
        """Drop pending data without persisting (topic deletion)."""
        self.buffer.discard()


class TopicManager:
    def __init__(self, filer_url: str):
        self.client = FilerClient(filer_url)
        self._partitions: dict[tuple, TopicPartition] = {}
        self._dead: set[tuple[str, str]] = set()  # tombstones until recreate
        self._lock = make_lock("TopicManager._lock")

    def conf_path(self, ns: str, topic: str) -> str:
        return f"{TOPICS_ROOT}/{ns}/{topic}/.conf"

    def create_topic(self, ns: str, topic: str, partitions: int = 4) -> dict:
        with self._lock:
            self._dead.discard((ns, topic))  # explicit recreate revives it
        conf = {"extended": {"partitions": str(partitions)}}
        self.client.create_entry(self.conf_path(ns, topic), conf)
        return {"namespace": ns, "topic": topic, "partitions": partitions}

    def topic_conf(self, ns: str, topic: str) -> Optional[dict]:
        e = self.client.get_entry(self.conf_path(ns, topic))
        if e is None:
            return None
        return {
            "namespace": ns,
            "topic": topic,
            "partitions": int(e.get("extended", {}).get("partitions", 1)),
        }

    def delete_topic(self, ns: str, topic: str) -> dict:
        """DeleteTopic rpc analog (messaging.proto): evict live partitions
        (discarding un-flushed data and JOINING in-flight flush threads — a
        late flush would resurrect the tree as orphan segments), tombstone
        the topic so concurrent publishes can't recreate a partition, then
        drop the log tree + conf from the filer. Filer I/O happens OUTSIDE
        the lock so a slow delete never stalls other topics' pub/sub."""
        with self._lock:
            self._dead.add((ns, topic))
            doomed = [
                self._partitions.pop(k)
                for k in [k for k in self._partitions
                          if k[0] == ns and k[1] == topic]
            ]
        for tp in doomed:
            tp.discard()
        self.client.delete(f"{TOPICS_ROOT}/{ns}/{topic}", recursive=True)
        return {"namespace": ns, "topic": topic, "deleted": True}

    def get_partition(self, ns: str, topic: str, partition: int) -> TopicPartition:
        key = (ns, topic, partition)
        with self._lock:
            tp = self._partitions.get(key)
            if tp is not None:
                return tp
            if (ns, topic) in self._dead:
                raise KeyError(f"no such topic {ns}/{topic}")
        # conf lookup = filer HTTP; never hold the global lock across it
        if self.topic_conf(ns, topic) is None:
            raise KeyError(f"no such topic {ns}/{topic}")
        with self._lock:
            tp = self._partitions.get(key)
            if tp is None:
                if (ns, topic) in self._dead:  # deleted while we looked
                    raise KeyError(f"no such topic {ns}/{topic}")
                tp = TopicPartition(self.client, ns, topic, partition)
                self._partitions[key] = tp
        return tp

    def close(self):
        with self._lock:
            for tp in self._partitions.values():
                tp.close()


class Broker:
    """HTTP pub/sub daemon. The reference speaks gRPC streams
    (`messaging_pb.SeaweedMessaging`, 6 rpcs); the poll-based HTTP surface
    here carries the same operations."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 17777,
        filer_url: str = "127.0.0.1:8888",
    ):
        self.host, self.port = host, port
        self.topics = TopicManager(filer_url)
        self._srv = None

    # /pub/<ns>/<topic>/<partition>
    def _h_pub(self, h, path, q, body):
        _, _, ns, topic, part = path.split("/", 4)
        try:
            tp = self.topics.get_partition(ns, topic, int(part))
        except KeyError as e:
            return 404, {"error": str(e)}
        key = base64.b64decode(h.headers.get("X-Msg-Key", "") or "")
        ts = tp.publish(key, body)
        if ts == 0:
            # the message was dropped, and acking it as 200 would lie to
            # the producer about durability. 410 only for a real deletion;
            # a broker mid-shutdown is retryable → 503
            if tp.buffer.discarded:
                return 410, {"error": f"topic {ns}/{topic} deleted"}
            return 503, {"error": "broker shutting down, retry"}
        return 200, {"ts_ns": ts}

    # /sub/<ns>/<topic>/<partition>?since_ns=&limit=
    def _h_sub(self, h, path, q, body):
        _, _, ns, topic, part = path.split("/", 4)
        try:
            tp = self.topics.get_partition(ns, topic, int(part))
        except KeyError as e:
            return 404, {"error": str(e)}
        # tolerant: a subscriber's garbage ?since_ns= must not 500 the broker
        msgs = tp.read(
            tolerant_uint(q.get("since_ns", 0), 0),
            tolerant_uint(q.get("limit", 1000), 1000),
        )
        out = [
            {
                "ts_ns": ts,
                "key": base64.b64encode(k).decode(),
                "value": base64.b64encode(v).decode(),
            }
            for ts, k, v in msgs
        ]
        return 200, {
            "messages": out,
            "last_ts_ns": out[-1]["ts_ns"]
            if out
            else tolerant_uint(q.get("since_ns", 0), 0),
        }

    # /topics/<ns>/<topic>
    def _h_topics(self, h, path, q, body):
        parts = path.split("/")
        if len(parts) < 4:
            return 400, {"error": "need /topics/<ns>/<topic>"}
        ns, topic = parts[2], parts[3]
        if h.command == "POST":
            if q.get("op") == "delete":
                return 200, self.topics.delete_topic(ns, topic)
            return 200, self.topics.create_topic(
                ns, topic, tolerant_uint(q.get("partitions", 4), 4)
            )
        conf = self.topics.topic_conf(ns, topic)
        if conf is None:
            return 404, {"error": "no such topic"}
        return 200, conf

    def _h_flush(self, h, path, q, body):
        for tp in list(self.topics._partitions.values()):
            tp.buffer.flush()
        return 200, {"ok": True}

    def start(self):
        broker = self

        class Handler(JsonHandler):
            routes = [
                ("POST", "/pub/", broker._h_pub),
                ("GET", "/sub/", broker._h_sub),
                ("POST", "/topics/", broker._h_topics),
                ("GET", "/topics/", broker._h_topics),
                ("POST", "/_flush", broker._h_flush),
            ]

        self._srv = start_server(Handler, self.host, self.port)
        return self

    def stop(self):
        self.topics.close()
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"
