"""Consistent-hash ring for partition→member placement (reference
`messaging/broker/consistent_distribution.go`, which wraps stathat/consistent:
20 virtual replicas per member, crc-style hashing, lookup by key).

Originally broker-only; now load-bearing for the sharded filer fleet
(filer/ring.py maps directory-tree shard keys onto filers with it), so
the corner cases are pinned by direct unit tests (test_consistent_ring):

- empty ring: ``get`` raises LookupError (callers own the "no members"
  story); single member: every key maps to it.
- determinism: the ring's layout is a pure function of its member SET —
  add/remove order never changes placement, and re-adding a removed
  member restores the exact previous layout (a reshard planned against
  ring A must equal one planned against a reconstructed A).
- duplicate virtual-node collisions: two members' virtual nodes may hash
  identically; ties break on the member name, so both survive, lookups
  stay deterministic, and removing one member never disturbs the other's
  nodes.
"""

from __future__ import annotations

import bisect
import hashlib


def _hash(key: "str | bytes") -> int:
    if isinstance(key, str):
        key = key.encode()
    return int.from_bytes(hashlib.md5(key).digest()[:8], "big")


class ConsistentRing:
    def __init__(self, replicas: int = 20):
        self.replicas = max(1, replicas)
        # sorted parallel arrays: _keys holds virtual-node hashes, _owners
        # the member each belongs to. Entries sort by (hash, member) so a
        # cross-member hash collision keeps BOTH nodes in a stable order
        # instead of one silently shadowing the other.
        self._keys: list[int] = []
        self._owners: list[str] = []
        self._members: set[str] = set()

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        self._rebuild()

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        self._rebuild()

    def _rebuild(self) -> None:
        # rebuilt from the member SET every time: layout is independent of
        # the add/remove sequence by construction
        ring = sorted(
            (_hash(f"{member}#{i}"), member)
            for member in self._members
            for i in range(self.replicas)
        )
        self._keys = [h for h, _ in ring]
        self._owners = [m for _, m in ring]

    def members(self) -> list[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def get(self, key: str) -> str:
        """The member owning ``key``: first virtual node clockwise of the
        key's hash. Raises LookupError on an empty ring."""
        if not self._members:
            raise LookupError("empty ring")
        if len(self._members) == 1:
            return next(iter(self._members))
        # bisect_right: a key hashing EXACTLY onto a virtual node walks
        # past all colliding nodes at that hash — deterministic regardless
        # of how many members collide there
        idx = bisect.bisect_right(self._keys, _hash(key)) % len(self._keys)
        return self._owners[idx]
