"""Consistent-hash ring for partition→broker placement (reference
`messaging/broker/consistent_distribution.go`, which wraps stathat/consistent:
20 virtual replicas per member, crc-style hashing, lookup by key)."""

from __future__ import annotations

import bisect
import hashlib


def _hash(key: "str | bytes") -> int:
    if isinstance(key, str):
        key = key.encode()
    return int.from_bytes(hashlib.md5(key).digest()[:8], "big")


class ConsistentRing:
    def __init__(self, replicas: int = 20):
        self.replicas = replicas
        self._ring: list[tuple[int, str]] = []
        self._members: set[str] = set()

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for i in range(self.replicas):
            self._ring.append((_hash(f"{member}#{i}"), member))
        self._ring.sort()

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        self._ring = [(h, m) for h, m in self._ring if m != member]

    def members(self) -> list[str]:
        return sorted(self._members)

    def get(self, key: str) -> str:
        if not self._ring:
            raise LookupError("empty ring")
        h = _hash(key)
        idx = bisect.bisect_right(self._ring, (h, "￿")) % len(self._ring)
        return self._ring[idx][1]
