"""In-memory message log with periodic flush (reference `util/log_buffer/
log_buffer.go:24,56`): appends accumulate in the active buffer; when the
buffer exceeds `flush_bytes` or `flush_interval` it is sealed, handed to the
flush function (persisted as a segment file), and kept in `prev_buffers` so
recent history stays readable from memory while persistence catches up.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional
from ..util.locks import make_lock

# flush_fn(start_ts_ns, stop_ts_ns, encoded_segment_bytes)
FlushFn = Callable[[int, int, bytes], None]


def encode_message(ts_ns: int, key: bytes, value: bytes) -> bytes:
    """Length-prefixed frame: 8B ts + 4B klen + key + 4B vlen + value."""
    return (
        ts_ns.to_bytes(8, "big")
        + len(key).to_bytes(4, "big")
        + key
        + len(value).to_bytes(4, "big")
        + value
    )


def decode_messages(blob: bytes) -> list[tuple[int, bytes, bytes]]:
    out = []
    pos = 0
    n = len(blob)
    while pos + 16 <= n:
        ts = int.from_bytes(blob[pos : pos + 8], "big")
        klen = int.from_bytes(blob[pos + 8 : pos + 12], "big")
        pos += 12
        key = blob[pos : pos + klen]
        pos += klen
        vlen = int.from_bytes(blob[pos : pos + 4], "big")
        pos += 4
        value = blob[pos : pos + vlen]
        pos += vlen
        out.append((ts, key, value))
    return out


class LogBuffer:
    def __init__(
        self,
        flush_fn: Optional[FlushFn] = None,
        flush_bytes: int = 4 * 1024 * 1024,
        flush_interval: float = 2.0,
        keep_prev: int = 8,
    ):
        self.flush_fn = flush_fn
        self.flush_bytes = flush_bytes
        self.flush_interval = flush_interval
        self.keep_prev = keep_prev
        self._buf = bytearray()
        self._msgs: list[tuple[int, bytes, bytes]] = []
        self._start_ts = 0
        self._prev: list[list[tuple[int, bytes, bytes]]] = []
        self._lock = make_lock("LogBuffer._lock")
        self._flushers: list[threading.Thread] = []
        self._last_flush = time.monotonic()
        self._stop = threading.Event()
        self.discarded = False  # True only via discard() (topic deletion)
        self._ticker = threading.Thread(target=self._tick, daemon=True)
        self._ticker.start()

    def append(self, key: bytes, value: bytes) -> int:
        with self._lock:
            if self._stop.is_set():
                # a handler holding a stale partition reference (obtained
                # before delete_topic evicted it) must not be able to seal
                # new segments into a deleted tree — drop, signalled by 0
                return 0
            ts = time.time_ns()
            if self._msgs and ts <= self._msgs[-1][0]:
                ts = self._msgs[-1][0] + 1  # strictly monotonic per partition
            if not self._msgs:
                self._start_ts = ts
            self._msgs.append((ts, key, value))
            self._buf += encode_message(ts, key, value)
            if len(self._buf) >= self.flush_bytes:
                self._seal_locked()
            return ts

    def _seal_locked(self) -> None:
        if not self._msgs:
            return
        msgs, blob = self._msgs, bytes(self._buf)
        start, stop = msgs[0][0], msgs[-1][0]
        self._prev.append(msgs)
        if len(self._prev) > self.keep_prev:
            self._prev = self._prev[-self.keep_prev :]
        self._msgs, self._buf = [], bytearray()
        self._last_flush = time.monotonic()
        if self.flush_fn:
            t = threading.Thread(
                target=self.flush_fn, args=(start, stop, blob), daemon=True
            )
            self._flushers = [f for f in self._flushers if f.is_alive()]
            self._flushers.append(t)
            t.start()

    def flush(self) -> None:
        with self._lock:
            self._seal_locked()

    def _tick(self) -> None:
        while not self._stop.wait(self.flush_interval / 2):
            with self._lock:
                if (
                    self._msgs
                    and time.monotonic() - self._last_flush > self.flush_interval
                ):
                    self._seal_locked()

    def read_since(self, ts_ns: int, limit: int = 1000):
        """Messages with ts > ts_ns still held in memory (active + prev)."""
        with self._lock:
            out = []
            for msgs in self._prev + [self._msgs]:
                for m in msgs:
                    if m[0] > ts_ns:
                        out.append(m)
                        if len(out) >= limit:
                            return out
            return out

    def memory_floor_ts(self) -> int:
        """Oldest ts still in memory (0 = everything is in memory)."""
        with self._lock:
            for msgs in self._prev + [self._msgs]:
                if msgs:
                    return msgs[0][0]
        return 0

    def close(self) -> None:
        self._stop.set()
        self.flush()

    def discard(self) -> None:
        """Stop WITHOUT persisting: drop pending messages and wait out any
        in-flight flush threads. For topic deletion — a flush landing after
        the topic tree is removed would resurrect it as orphan segments."""
        self._stop.set()
        with self._lock:
            self.discarded = True
            self._msgs, self._buf = [], bytearray()
            self._prev = []
            self.flush_fn = None  # no late _seal_locked may ever persist
            flushers = list(self._flushers)
            self._flushers = []
        for t in flushers:
            t.join(timeout=10)
