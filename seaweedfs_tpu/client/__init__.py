"""Standalone client package: the HDFS-gateway / Java-client analog.

The reference ships `other/java/client` (FilerClient.java:1 — entry CRUD +
chunked IO) and `other/java/hdfs2` (SeaweedFileSystem.java:1 — a Hadoop
`FileSystem` so Spark/Hive/MapReduce can mount the filer). The Python-era
equivalent of "the Hadoop ecosystem can mount it" is fsspec: pandas,
pyarrow, dask, duckdb and torch data loaders all speak
`fsspec.AbstractFileSystem`. This package provides that adapter plus a
plain `FilerClient` for entry-level access.

Usage::

    import fsspec
    from seaweedfs_tpu.client import register
    register()
    fs = fsspec.filesystem("seaweedfs", filer="127.0.0.1:8888")
    fs.ls("/")
    with fs.open("/data/part-0.parquet", "rb") as f: ...

or URL-style, once registered: ``fsspec.open("seaweedfs://127.0.0.1:8888/a/b")``.
"""

from ..filer.client import FilerClient  # noqa: F401 — entry-level client
from .fs import SeaweedFile, SeaweedFileSystem, register  # noqa: F401

__all__ = ["FilerClient", "SeaweedFile", "SeaweedFileSystem", "register"]
