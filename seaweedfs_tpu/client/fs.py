"""fsspec `AbstractFileSystem` over the filer HTTP API.

The reference's HDFS adapter (`other/java/hdfs2/.../SeaweedFileSystem.java:1`)
maps Hadoop `FileSystem` calls onto the filer gRPC surface, streaming file
bytes chunk-by-chunk to volume servers (`SeaweedOutputStream.java:1`) and
reading with ranged chunk views (`SeaweedInputStream.java:1`). This is the
same design over this repo's HTTP/JSON surface:

- reads: ranged GETs against the filer (which serves them from chunk views
  + the tiered chunk cache);
- writes: chunk-size pieces are assigned + uploaded straight to volume
  servers (filer `/_assign`), and the entry (chunk list) is committed to
  the filer on close — big files never buffer whole in memory and the
  bytes take one hop, exactly like the Java SeaweedOutputStream;
- listings/metadata: the filer's JSON listing and `?meta=true` entries.
"""

from __future__ import annotations

import time
from typing import Optional

from fsspec import AbstractFileSystem
from fsspec.spec import AbstractBufferedFile

from ..filer.client import FilerClient  # noqa: F401 — re-exported for callers
from ..filer.entry import Entry
from ..filer.ring import make_client


def _entry_info(d: dict, path: str) -> dict:
    e = Entry.from_dict(d) if "full_path" in d else None
    size = e.file_size() if e else d.get("size", 0)
    is_dir = d.get("is_directory", False)
    return {
        # root_marker is "/": names are absolute, like the local and hdfs
        # fsspec implementations (pyarrow datasets rely on ls names being
        # inside the base dir verbatim)
        "name": path,
        "size": 0 if is_dir else size,
        "type": "directory" if is_dir else "file",
        "mtime": d.get("mtime", 0),
        "mode": d.get("mode", 0o660),
        "mime": d.get("mime", ""),
        "collection": d.get("collection", ""),
    }


class SeaweedFileSystem(AbstractFileSystem):
    """`fsspec.filesystem("seaweedfs", filer="host:port")`.

    Parity target: `SeaweedFileSystem.java` (mkdirs/open/create/rename/
    delete/listStatus/getFileStatus) — same operation set, fsspec names.
    """

    protocol = ("seaweedfs", "swfs")
    root_marker = "/"

    def __init__(
        self,
        filer: str = "127.0.0.1:8888",
        chunk_size: int = 8 * 1024 * 1024,
        collection: str = "",
        ttl: str = "",
        cipher: Optional[bool] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.filer = filer
        # "host:p1,host:p2" (or a list) → ring-aware client that routes
        # each path to its owning filer; one address stays the plain
        # FilerClient (filer/ring.py make_client)
        self.client = make_client(filer)
        self.chunk_size = chunk_size
        self.collection = collection
        self.ttl = ttl
        if cipher is None:
            # honor the filer's -encryptVolumeData the way the mount does
            # (wfs.go GetFilerConfiguration) — a direct-to-volume writer
            # that skipped encryption would silently store plaintext
            try:
                cipher = bool(self.client.status().get("cipher", False))
            except Exception:
                cipher = False
        self.cipher = cipher

    # -- path/url plumbing ----------------------------------------------------
    @classmethod
    def _strip_protocol(cls, path):
        path = super()._strip_protocol(path)
        # seaweedfs://host:port/a/b → the netloc is connection info (it is
        # returned via _get_kwargs_from_urls), the path is /a/b
        if "/" in path and ":" in path.split("/", 1)[0]:
            path = "/" + path.split("/", 1)[1]
        elif ":" in path.split("/", 1)[0]:
            path = "/"
        if not path.startswith("/"):
            path = "/" + path
        return path.rstrip("/") or "/"

    @staticmethod
    def _get_kwargs_from_urls(path):
        from urllib.parse import urlsplit

        parts = urlsplit(path)
        return {"filer": parts.netloc} if parts.netloc else {}

    # -- metadata -------------------------------------------------------------
    def info(self, path, **kwargs):
        path = self._strip_protocol(path)
        if path == "/":
            return {"name": "/", "size": 0, "type": "directory", "mtime": 0}
        d = self.client.get_entry(path)
        if d is None:
            raise FileNotFoundError(path)
        return _entry_info(d, path)

    def ls(self, path, detail=False, **kwargs):
        path = self._strip_protocol(path)
        info = self.info(path)
        if info["type"] != "directory":
            return [info] if detail else [info["name"]]
        out, cursor = [], ""
        while True:
            page = self.client.list(path, start_after=cursor, limit=1000)
            if not page:
                break
            for d in page:
                name = d.get("name") or d.get("full_path", "").rsplit("/", 1)[-1]
                child = (path.rstrip("/") + "/" + name) if path != "/" else "/" + name
                out.append(_entry_info(d, child))
                cursor = name
            if len(page) < 1000:
                break
        return out if detail else [o["name"] for o in out]

    def exists(self, path, **kwargs):
        try:
            self.info(path)
            return True
        except FileNotFoundError:
            return False

    # -- directory ops --------------------------------------------------------
    def mkdir(self, path, create_parents=True, **kwargs):
        path = self._strip_protocol(path)
        if path == "/":
            return
        self.client.mkdir(path)

    def makedirs(self, path, exist_ok=False):
        path = self._strip_protocol(path)
        if not exist_ok and self.exists(path):
            raise FileExistsError(path)
        self.mkdir(path)  # the filer auto-creates parent directories

    def rmdir(self, path):
        path = self._strip_protocol(path)
        st = self.client.delete(path)
        if st == 404:
            raise FileNotFoundError(path)
        if st >= 400:
            raise OSError(f"rmdir {path}: HTTP {st}")

    def _rm(self, path):
        path = self._strip_protocol(path)
        st = self.client.delete(path)
        if st == 404:
            raise FileNotFoundError(path)

    def rm(self, path, recursive=False, maxdepth=None):
        path = self._strip_protocol(path)
        st = self.client.delete(path, recursive=recursive)
        if st == 404:
            raise FileNotFoundError(path)
        if st >= 400:
            raise OSError(f"rm {path}: HTTP {st}")

    def mv(self, path1, path2, **kwargs):
        path1, path2 = self._strip_protocol(path1), self._strip_protocol(path2)
        if not self.exists(path1):
            raise FileNotFoundError(path1)
        self.client.rename(path1, path2)

    def cp_file(self, path1, path2, **kwargs):
        # no server-side copy rpc in the reference either (distcp reads +
        # rewrites); stream through chunk-size pieces
        with self.open(path1, "rb") as src, self.open(path2, "wb") as dst:
            while True:
                block = src.read(self.chunk_size)
                if not block:
                    break
                dst.write(block)

    def created(self, path):
        d = self.client.get_entry(self._strip_protocol(path))
        if d is None:
            raise FileNotFoundError(path)
        return d.get("crtime", 0)

    def modified(self, path):
        return self.info(path)["mtime"]

    # -- file IO --------------------------------------------------------------
    def _open(self, path, mode="rb", block_size=None, autocommit=True,
              cache_options=None, **kwargs):
        return SeaweedFile(
            self, self._strip_protocol(path), mode,
            block_size=block_size or self.chunk_size,
            autocommit=autocommit, cache_options=cache_options, **kwargs,
        )

    def cat_file(self, path, start=None, end=None, **kwargs):
        path = self._strip_protocol(path)
        rng = None
        if start is not None or end is not None:
            info = self.info(path)
            s = start or 0
            if s < 0:
                s += info["size"]
            e = info["size"] if end is None else (end if end >= 0 else end + info["size"])
            if e <= s:
                return b""
            rng = f"bytes={s}-{e - 1}"
        status, body, _ = self.client.get_object(path, rng=rng)
        if status == 404:
            raise FileNotFoundError(path)
        if status >= 400 and status != 416:
            raise OSError(f"read {path}: HTTP {status}")
        return b"" if status == 416 else body

    def pipe_file(self, path, value, **kwargs):
        with self.open(path, "wb") as f:
            f.write(value)

    def _wfs(self):
        """Shared chunk writer (assign → upload → cipher), lazy."""
        if getattr(self, "_wfs_inst", None) is None:
            from ..mount.wfs import WFS

            self._wfs_inst = WFS(
                self.filer, chunk_size=self.chunk_size,
                collection=self.collection, ttl=self.ttl,
                use_meta_cache=False, cipher=self.cipher,
            )
        return self._wfs_inst


class SeaweedFile(AbstractBufferedFile):
    """Ranged reads; writes stream chunk-size pieces straight to volume
    servers and commit the entry on close (SeaweedOutputStream.java:1)."""

    def __init__(self, fs: SeaweedFileSystem, path: str, mode: str = "rb",
                 **kwargs):
        self._chunks: list = []
        self._append_base = 0
        super().__init__(fs, path, mode, **kwargs)

    # -- read side ------------------------------------------------------------
    def _fetch_range(self, start: int, end: int) -> bytes:
        if end <= start:
            return b""
        status, body, _ = self.fs.client.get_object(
            self.path, rng=f"bytes={start}-{end - 1}"
        )
        if status == 404:
            raise FileNotFoundError(self.path)
        if status == 416:
            return b""
        if status >= 400:
            raise OSError(f"read {self.path}: HTTP {status}")
        return body

    # -- write side -----------------------------------------------------------
    def _initiate_upload(self):
        self._chunks = []
        self._append_base = 0
        if "a" in self.mode:
            # append: keep the existing chunk list; new chunks land after it
            d = self.fs.client.get_entry(self.path)
            if d is not None:
                e = Entry.from_dict(d)
                self._chunks = list(e.chunks)
                self._append_base = e.file_size()

    def _upload_chunk(self, final=False) -> bool:
        data = self.buffer.getvalue()
        if data:
            base = self._append_base + (self.offset or 0)
            self._chunks.extend(self.fs._wfs().save_data_as_chunks(data, base))
        if final:
            entry = Entry(
                full_path=self.path,
                is_directory=False,
                mtime=int(time.time()),
                mime="application/octet-stream",
                collection=self.fs.collection,
                chunks=list(self._chunks),
            )
            self.fs.client.create_entry(self.path, entry.to_dict())
        return True


def register() -> None:
    """Register the 'seaweedfs' / 'swfs' protocols with fsspec."""
    import fsspec

    fsspec.register_implementation(
        "seaweedfs", SeaweedFileSystem, clobber=True
    )
    fsspec.register_implementation("swfs", SeaweedFileSystem, clobber=True)
