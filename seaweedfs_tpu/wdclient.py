"""wdclient: client-side master session with a cached volume-location map.

Reference: `weed/wdclient/masterclient.go:16,48,96` (KeepConnectedToMaster
subscribing to the master's VolumeLocation push stream) and
`weed/wdclient/vid_map.go:24,49,70` (the vid → locations cache behind
`LookupFileId`). Filers and gateways hold one of these so hot-path reads
never block on a master round-trip.

TPU-native transport note: the reference's bidi gRPC stream becomes an HTTP
long-poll against `/cluster/watch` (same versioned-delta semantics: the
master resends a full snapshot when the client falls behind the retained
log, exactly like a stream reconnect replays everything).
"""

from __future__ import annotations

import random
import threading
from typing import Optional

from .server.http_util import http_json
from .storage.file_id import FileId


class Location:
    __slots__ = ("url", "public_url")

    def __init__(self, url: str, public_url: str = ""):
        self.url = url
        self.public_url = public_url or url

    def __eq__(self, other):
        return isinstance(other, Location) and self.url == other.url

    def __hash__(self):
        return hash(self.url)

    def __repr__(self):
        return f"Location({self.url})"


class VidMap:
    """vid → [Location] cache (wdclient/vid_map.go:24)."""

    def __init__(self):
        self._locations: dict[int, list[Location]] = {}
        self._lock = threading.RLock()

    def lookup_volume(self, vid: int) -> list[Location]:
        with self._lock:
            return list(self._locations.get(vid, ()))

    def lookup_volume_url(self, vid: int) -> Optional[str]:
        locs = self.lookup_volume(vid)
        return random.choice(locs).url if locs else None

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            locs = self._locations.setdefault(vid, [])
            if loc not in locs:
                locs.append(loc)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            locs = self._locations.get(vid)
            if locs:
                self._locations[vid] = [l for l in locs if l.url != url]
                if not self._locations[vid]:
                    del self._locations[vid]

    def invalidate(self, vid: int) -> None:
        """Drop every cached location for vid (stale-read eviction)."""
        with self._lock:
            self._locations.pop(vid, None)

    def replace_all(self, snapshot: dict) -> None:
        """Install a full vid → [{url, public_url}] snapshot."""
        fresh = {
            int(vid): [Location(m["url"], m.get("public_url", "")) for m in locs]
            for vid, locs in snapshot.items()
        }
        with self._lock:
            self._locations = fresh

    def __len__(self):
        with self._lock:
            return len(self._locations)


def find_reachable_master(seeds: list[str], timeout: float = 2.0,
                          strict: bool = False) -> str:
    """First seed answering /cluster/status. Reachable beats leader-
    guessing: followers PROXY leader-only ops (master_server._leader_only),
    while a reported leader may itself be dead — never pin to an address
    nobody verified. When none answer: '' under strict (callers that must
    not act on an unverified address), else the first seed."""
    for m in seeds:
        try:
            http_json("GET", f"http://{m}/cluster/status", timeout=timeout)
            return m
        except Exception:  # sweedlint: ok broad-except seed probe; an unreachable master is the expected case
            continue
    if strict:
        return ""
    return seeds[0] if seeds else ""


class MasterClient:
    """Keeps a VidMap fresh by long-polling the master's location feed
    (wdclient/masterclient.go KeepConnectedToMaster); falls back to a
    synchronous `/dir/lookup` on cache miss."""

    def __init__(
        self,
        masters: list[str] | str,
        client_name: str = "client",
        poll_timeout: float = 10.0,
    ):
        self.masters = [masters] if isinstance(masters, str) else list(masters)
        self.client_name = client_name
        self.poll_timeout = poll_timeout
        self.vid_map = VidMap()
        self.current_master: Optional[str] = None
        self._version = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- leader discovery (masterclient.go:48 tryAllMasters) ------------------
    def _find_master(self) -> Optional[str]:
        for m in self.masters:
            try:
                st = http_json("GET", f"http://{m}/cluster/status", timeout=3.0)
                leader = st.get("leader") or m
                return leader
            except Exception:  # sweedlint: ok broad-except master probe; try the next seed
                continue
        return None

    # -- background keep-connected loop ---------------------------------------
    def start(self) -> "MasterClient":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            master = self._find_master()
            if master is None:
                self._stop.wait(1.0)
                continue
            if master != self.current_master:
                # new session: bootstrap from a full snapshot, like a fresh
                # KeepConnected stream receiving the complete location set
                self.current_master = master
                self._version = -1
            try:
                r = http_json(
                    "GET",
                    f"http://{master}/cluster/watch"
                    f"?since={self._version}&timeout={self.poll_timeout}",
                    timeout=self.poll_timeout + 20.0,
                )
            except Exception:
                r = None
            if r is None or r.get("error") or "version" not in r:
                # transport failure OR an error-shaped body (http_json maps
                # HTTP errors to {'error': ...} instead of raising): back
                # off and resync from a fresh snapshot
                self.current_master = None
                self._stop.wait(0.5)
                continue
            self._apply(r)

    def _apply(self, r: dict) -> None:
        if "snapshot" in r:
            self.vid_map.replace_all(r["snapshot"])
        else:
            for e in r.get("events", ()):
                loc = Location(e["url"], e.get("public_url", ""))
                if e.get("deleted"):
                    self.vid_map.delete_location(e["vid"], e["url"])
                else:
                    self.vid_map.add_location(e["vid"], loc)
        self._version = r.get("version", self._version)

    # -- lookups (vid_map.go:49 LookupFileId) ---------------------------------
    def lookup_volume(self, vid: int) -> list[Location]:
        locs = self.vid_map.lookup_volume(vid)
        if locs:
            return locs
        master = self.current_master or self._find_master()
        if master is None:
            return []
        try:
            r = http_json("GET", f"http://{master}/dir/lookup?volumeId={vid}")
        except Exception:
            return []
        for m in r.get("locations", ()):
            self.vid_map.add_location(
                vid,
                Location(m["url"], m.get("public_url") or m.get("publicUrl", "")),
            )
        return self.vid_map.lookup_volume(vid)

    def lookup_file_id(self, fid: str) -> list[str]:
        """fid → full http urls, like vid_map.go:49 LookupFileId."""
        file_id = FileId.parse(fid)
        return [
            f"http://{loc.url}/{fid}" for loc in self.lookup_volume(file_id.volume_id)
        ]
