"""Volume/needle TTLs: 2-byte (count, unit) encoding.

Matches `weed/storage/needle/volume_ttl.go`: units are minute/hour/day/week/
month/year stored as 1..6; human strings like "3m", "4h", "5d", "6w", "7M",
"8y" (bare digits mean minutes).
"""

from __future__ import annotations

from dataclasses import dataclass

EMPTY = 0
MINUTE = 1
HOUR = 2
DAY = 3
WEEK = 4
MONTH = 5
YEAR = 6

_UNIT_FROM_CHAR = {"m": MINUTE, "h": HOUR, "d": DAY, "w": WEEK, "M": MONTH, "y": YEAR}
_CHAR_FROM_UNIT = {v: k for k, v in _UNIT_FROM_CHAR.items()}
_MINUTES = {
    EMPTY: 0,
    MINUTE: 1,
    HOUR: 60,
    DAY: 60 * 24,
    WEEK: 60 * 24 * 7,
    MONTH: 60 * 24 * 31,
    YEAR: 60 * 24 * 365,
}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = EMPTY

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return ((self.count & 0xFF) << 8) | (self.unit & 0xFF)

    def minutes(self) -> int:
        return self.count * _MINUTES.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0 or self.unit == EMPTY:
            return ""
        return f"{self.count}{_CHAR_FROM_UNIT.get(self.unit, '')}"

    def __bool__(self) -> bool:
        return self.count != 0 and self.unit != EMPTY


EMPTY_TTL = TTL()


def read_ttl(s: str) -> TTL:
    """Parse a human TTL string (volume_ttl.go:35-49)."""
    if not s:
        return EMPTY_TTL
    unit_char = s[-1]
    if unit_char.isdigit():
        count_str, unit = s, MINUTE
    else:
        count_str, unit = s[:-1], _UNIT_FROM_CHAR.get(unit_char, EMPTY)
    count = int(count_str)
    if not 0 <= count <= 255:
        raise ValueError(f"ttl count {count} out of byte range")
    return TTL(count, unit)


def load_ttl_from_bytes(b: bytes) -> TTL:
    if b[0] == 0 and b[1] == 0:
        return EMPTY_TTL
    return TTL(b[0], b[1])


def load_ttl_from_uint32(v: int) -> TTL:
    return load_ttl_from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))
