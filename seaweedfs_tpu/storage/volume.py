"""Volume: one append-only .dat + .idx pair holding millions of needles.

Mirrors `weed/storage/volume.go` + `volume_read_write.go` + `volume_loading.go`
+ `volume_checking.go` + `volume_vacuum.go`:

- writes append to .dat and log to .idx (offsets 8-byte aligned, stored /8);
- deletes append a zero-data needle then log a tombstone .idx entry;
- reads look up the in-memory needle map, CRC-verify, honor TTL expiry;
- on load the last ≤10 .idx entries are verified against the .dat and a torn
  tail is truncated (CheckAndFixVolumeDataIntegrity);
- vacuum (compact) rewrites live needles to .cpd/.cpx and commits by rename,
  bumping the superblock compaction revision.

Concurrency: one RLock-style mutex per volume; the reference's async batching
worker (volume_read_write.go:306) is a fsync-amortization strategy — here
writes are synchronous and `sync()` is explicit (callers batch).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Callable, Iterator, Optional

from ..stats.heat import EwmaHeat
from ..util.locks import make_rlock
from ..util import faultpoints
from .backend import BackendStorageFile, DiskFile
from .needle import (
    CURRENT_VERSION,
    Needle,
    get_actual_size,
    needle_body_length,
    parse_needle_header,
)
from .needle_map import CompactNeedleMap, NeedleValue
from .replica_placement import ReplicaPlacement
from .super_block import SUPER_BLOCK_SIZE, SuperBlock
from .ttl import EMPTY_TTL, TTL
from .types import (
    NEEDLE_HEADER_SIZE,
    NEEDLE_PADDING_SIZE,
    OFFSET_SIZE,
    max_possible_volume_size,
    size_is_valid,
)


class NotFoundError(Exception):
    pass


class DeletedError(Exception):
    pass


class VolumeError(Exception):
    pass


def volume_file_name(directory: str, collection: str, vid: int) -> str:
    """`<dir>/<collection>_<vid>` or `<dir>/<vid>` (volume.go FileName)."""
    if collection:
        return os.path.join(directory, f"{collection}_{vid}")
    return os.path.join(directory, str(vid))


class Volume:
    def __init__(
        self,
        directory: str,
        collection: str,
        vid: int,
        replica_placement: Optional[ReplicaPlacement] = None,
        ttl: TTL = EMPTY_TTL,
        version: int = CURRENT_VERSION,
        offset_size: int = OFFSET_SIZE,
        create_if_missing: bool = True,
        needle_map_kind: str = "dense",
    ):
        self.dir = directory
        self.collection = collection
        self.id = vid
        self.offset_size = offset_size
        # native turbo engine (native/turbo.py); while attached, the engine
        # is the single writer of .dat/.idx and owns the needle map
        self.turbo = None
        self._turbo_writable_http = True
        # needle map kind (needle_map.go:12-19): "dense" = 16B/entry packed
        # arrays (the reference's in-memory CompactMap profile), "memory" =
        # plain dict, "sqlite" = on-disk B-tree for RAM-exceeding volumes
        # (the leveldb kind)
        self.needle_map_kind = needle_map_kind
        self._read_only = False
        self.last_append_at_ns = 0
        self.last_modified_ts_seconds = 0
        self._lock = make_rlock("Volume._lock")
        self._is_compacting = False
        # zipfian-skew signal: decayed op counters marked by the store's
        # routing layer, shipped in heartbeats for heat-aware placement
        self.read_heat = EwmaHeat()
        self.write_heat = EwmaHeat()

        base = self.file_name()
        tier_exists = os.path.exists(base + ".tier")
        dat_exists = os.path.exists(base + ".dat") or tier_exists
        if not dat_exists and not create_if_missing:
            raise FileNotFoundError(base + ".dat")

        if tier_exists:
            # sealed volume whose .dat lives on a remote tier
            import json as _json

            from .backend import RemoteS3File

            with open(base + ".tier") as f:
                info = _json.load(f)
            endpoint, ak, sk = Volume._tier_credentials(info)
            self.data_backend: BackendStorageFile = RemoteS3File(
                endpoint,
                info["bucket"],
                info["key"],
                ak,
                sk,
                size=info["size"],
            )
            self.read_only = True
        else:
            self.data_backend = DiskFile(base + ".dat", create=True)
        if dat_exists and self.data_backend.size() >= SUPER_BLOCK_SIZE:
            import struct as _struct

            head = self.data_backend.read_at(0, SUPER_BLOCK_SIZE)
            extra_size = _struct.unpack(">H", head[6:8])[0]
            self.super_block = SuperBlock.from_bytes(
                self.data_backend.read_at(0, SUPER_BLOCK_SIZE + extra_size)
            )
        else:
            self.super_block = SuperBlock(
                version=version,
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl,
            )
            self.data_backend.write_at(0, self.super_block.to_bytes())

        idx_path = base + ".idx"
        if not os.path.exists(idx_path) and dat_exists:
            self._rebuild_index(idx_path)
        # unbuffered: .idx appends must be immediately visible to other
        # readers of the file (EC encode reads the .idx of a live volume).
        # One 16-byte write(2) per put matches the reference's os.File.Write.
        idx_file = open(idx_path, "a+b", buffering=0)
        try:
            # ownership transfers to the needle map (nm.close() closes it);
            # until then a load failure must not leak the unbuffered fd
            self.nm = self._load_needle_map(idx_file)
            self.last_append_at_ns = self._check_and_fix_integrity(idx_file)
        except Exception:
            idx_file.close()
            raise

    def _load_needle_map(self, idx_file):
        kind = self.needle_map_kind
        if kind == "memory":
            return CompactNeedleMap.load(idx_file, self.offset_size)
        if kind == "dense":
            from .needle_map_dense import DenseNeedleMap

            return DenseNeedleMap.load(idx_file, self.offset_size)
        if kind == "sqlite":
            from .needle_map_dense import SqliteNeedleMap

            return SqliteNeedleMap.load(
                idx_file, self.file_name() + ".ldb", self.offset_size
            )
        if kind == "mmap":
            # billion-needle kind: sorted .mdx base memory-mapped read-only
            # + overflow dict; near-zero RSS at any entry count
            from .needle_map_dense import MmapNeedleMap

            return MmapNeedleMap.load(
                idx_file, self.file_name() + ".mdx", self.offset_size
            )
        if kind == "sorted":
            # read-only kind for sealed volumes (needle_map_sorted_file.go):
            # generate/refresh the .sdx from the .idx, then binary-search it
            # on disk with zero resident entries
            from .needle_map_dense import (
                SortedFileNeedleMap,
                write_sorted_index,
            )

            base = self.file_name()
            sdx, idxp = base + ".sdx", base + ".idx"
            if not os.path.exists(sdx) or (
                os.path.getmtime(sdx) < os.path.getmtime(idxp)
            ):
                with open(idxp, "rb") as f:
                    write_sorted_index(f.read(), sdx, self.offset_size)
            # sweedlint: ok lock-discipline load path; runs in __init__ before the volume is shared
            self.read_only = True
            return SortedFileNeedleMap(sdx, self.offset_size, idx_file)
        raise ValueError(f"unknown needle map kind {kind!r}")

    # -- native turbo attach/detach ------------------------------------------
    @property
    def read_only(self) -> bool:
        return self._read_only

    @read_only.setter
    def read_only(self, value: bool) -> None:
        self._read_only = value
        if self.turbo is not None:  # sweedlint: ok lock-discipline GIL-atomic reference read; attach/detach swap it under the lock
            self.turbo.set_readonly(self.id, value)

    def attach_turbo(self, engine, writable_http: bool = True) -> bool:
        """Hand the data plane to the native engine.  Refused for volume
        kinds the engine can't own safely (sorted/sealed maps, remote-tier
        backends, volume-level TTL inheritance)."""
        if self.turbo is not None:  # sweedlint: ok lock-discipline admin pre-check; attach is store-serialized, worst case re-attach returns True
            return True
        if self.needle_map_kind in ("sorted", "mmap"):
            # sorted is sealed/read-only; mmap's base is an immutable
            # mapping the engine can't own as its writable .idx-backed map
            return False
        # sweedlint: ok lock-discipline admin pre-check; tier moves exclude attach via the store
        if not isinstance(self.data_backend, DiskFile):
            return False  # remote tier: reads go through S3
        if self.ttl != EMPTY_TTL:
            return False  # native writer doesn't inherit volume TTLs
        from ..native.turbo import TurboNeedleMap

        base = self.file_name()
        with self._lock:
            self.sync()  # sweedlint: ok blocking-under-lock flush-before-handoff; the native engine must see a complete .dat
            if not engine.register(
                self.id, base + ".dat", base + ".idx", self.version,
                self.offset_size, writable_http, self._read_only,
            ):
                return False
            idx_file = self.nm._index_file
            self.nm.release()
            self.nm = TurboNeedleMap(engine, self.id, idx_file,
                                     self.offset_size)
            self.turbo = engine
            self._turbo_writable_http = writable_http
        return True

    def detach_turbo(self, reload_map: bool = True) -> None:
        """Take the data plane back; reload the Python needle map from the
        .idx the engine kept current."""
        if self.turbo is None:  # sweedlint: ok lock-discipline admin pre-check; the locked block re-reads the reference
            return
        with self._lock:
            engine = self.turbo
            self.turbo = None
            engine.unregister(self.id)
            idx_file = self.nm._index_file
            if reload_map:
                self.nm = self._load_needle_map(idx_file)
            else:
                self.nm = CompactNeedleMap(idx_file, self.offset_size)

    def _turbo_reattach_ctx(self):
        """Context manager: detach for a file-rewriting operation, re-attach
        after (used by compact)."""
        import contextlib

        vol = self

        @contextlib.contextmanager
        def ctx():
            engine = vol.turbo
            writable = vol._turbo_writable_http
            vol.detach_turbo()
            try:
                yield
            finally:
                if engine is not None:
                    vol.attach_turbo(engine, writable)

        return ctx()

    # -- identity ------------------------------------------------------------
    def file_name(self) -> str:
        return volume_file_name(self.dir, self.collection, self.id)

    @property
    def version(self) -> int:
        # sweedlint: ok lock-discipline GIL-atomic reference read; only the locked compact commit replaces super_block
        return self.super_block.version

    @property
    def ttl(self) -> TTL:
        # sweedlint: ok lock-discipline GIL-atomic reference read; only the locked compact commit replaces super_block
        return self.super_block.ttl

    def content_size(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; nm reference swaps are GIL-atomic
        return self.nm.content_size()

    def deleted_size(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; nm reference swaps are GIL-atomic
        return self.nm.deleted_size()

    def file_count(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; nm reference swaps are GIL-atomic
        return self.nm.file_count()

    def deleted_count(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; nm reference swaps are GIL-atomic
        return self.nm.deleted_count()

    def max_file_key(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; nm reference swaps are GIL-atomic
        return self.nm.max_file_key

    def size(self) -> int:
        # sweedlint: ok lock-discipline heartbeat stat read; backend reference swaps are GIL-atomic
        return self.data_backend.size()

    def garbage_level(self) -> float:
        """Vacuum-triggering ratio: deleted bytes / all content bytes ever
        written (volume.go garbageLevel — ContentSize accumulates every put)."""
        if self.content_size() == 0:
            return 0.0
        return self.deleted_size() / self.content_size()

    # -- load-time integrity (volume_checking.go:16-44) ----------------------
    def _check_and_fix_integrity(self, idx_file) -> int:
        entry_size = 8 + self.offset_size + 4
        idx_file.flush()
        idx_size = os.path.getsize(idx_file.name)
        if idx_size % entry_size:
            idx_size -= idx_size % entry_size
            idx_file.truncate(idx_size)
        if idx_size == 0:
            return 0
        from . import idx as idx_mod

        healthy = idx_size
        last_append_at_ns = 0
        last_good: Optional[tuple[int, int, int]] = None
        with open(idx_file.name, "rb") as f:
            for i in range(1, 11):
                off = idx_size - i * entry_size
                if off < 0:
                    break
                f.seek(off)
                key, aoff, size = idx_mod.unpack_entry(
                    f.read(entry_size), self.offset_size
                )
                ok, ns = self._verify_entry(key, aoff, size)
                if ok:
                    last_append_at_ns = ns
                    last_good = (key, aoff, size)
                    break
                healthy = off
        if healthy < idx_size:
            idx_file.truncate(healthy)
            # reload the map (entries AND counters) without the torn tail;
            # release() drops any auxiliary handles (sqlite db) while the
            # shared idx handle stays open
            # sweedlint: ok lock-discipline load path; runs in __init__ before the volume is shared
            self.nm.release()
            # sweedlint: ok lock-discipline load path; runs in __init__ before the volume is shared
            self.nm = self._load_needle_map(idx_file)
        # Truncate any garbage .dat tail past the last verified record —
        # otherwise the next append starts at an unaligned/torn offset. (The
        # reference leaves the tail and its ToOffset silently rounds the
        # next append's offset down — a latent corruption; we cut instead.)
        if last_good is not None:
            _, aoff, size = last_good
            record_end = aoff + get_actual_size(max(size, 0), self.version)
            if self.data_backend.size() > record_end:  # sweedlint: ok lock-discipline load path; runs in __init__ before the volume is shared
                self.data_backend.truncate(record_end)
        return last_append_at_ns

    def _verify_entry(self, key: int, aoff: int, size: int) -> tuple[bool, int]:
        if aoff == 0 and size == 0:
            return True, 0
        if size < 0:
            # tombstone entries point at the appended deletion needle
            # (verifyDeletedNeedleIntegrity): check it exists and matches
            blob_len = get_actual_size(0, self.version)
            # sweedlint: ok lock-discipline called from the __init__ load path only
            blob = self.data_backend.read_at(aoff, blob_len)
            if len(blob) < blob_len:
                return False, 0
            try:
                _, nid, nsize = parse_needle_header(blob[:NEEDLE_HEADER_SIZE])
                if nid != key or nsize != 0:
                    return False, 0
                n = Needle.from_bytes(blob, 0, self.version)
            except Exception:
                return False, 0
            return True, n.append_at_ns
        blob_len = get_actual_size(size, self.version)
        # sweedlint: ok lock-discipline called from the __init__ load path only
        blob = self.data_backend.read_at(aoff, blob_len)
        if len(blob) < blob_len:
            return False, 0
        try:
            cookie, nid, nsize = parse_needle_header(blob[:NEEDLE_HEADER_SIZE])
            if nid != key or nsize != size:
                return False, 0
            n = Needle.from_bytes(blob, size, self.version)
        except Exception:
            return False, 0
        return True, n.append_at_ns

    def _rebuild_index(self, idx_path: str) -> None:
        """Scan the .dat and regenerate the .idx (super_block → needles)."""
        from . import idx as idx_mod

        with open(idx_path, "wb") as out:
            for n, offset, _body_len in self.scan_needles(verify_crc=False):
                if n.size > 0 or n.data:
                    out.write(
                        idx_mod.pack_entry(n.id, offset, n.size, self.offset_size)
                    )
                else:
                    out.write(idx_mod.pack_entry(n.id, offset, -1, self.offset_size))

    # -- write path (volume_read_write.go:78-128) ----------------------------
    def write_needle(
        self,
        n: Needle,
        fsync: bool = False,
        append_at_ns: Optional[int] = None,
    ) -> tuple[int, int, bool]:
        """Returns (offset, size, is_unchanged)."""
        if n.ttl == EMPTY_TTL and self.ttl != EMPTY_TTL:
            from .needle import FLAG_HAS_TTL

            n.set_flag(FLAG_HAS_TTL)
            n.ttl = self.ttl
        with self._lock:
            # under the lock: a write must not race past a concurrent
            # mark-readonly (seal / tier move)
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read only")
            actual_size = get_actual_size(len(n.data), self.version)
            if max_possible_volume_size(self.offset_size) < (
                self.nm.content_size() + actual_size
            ):
                raise VolumeError(
                    f"volume {self.id} size limit exceeded "
                    f"(content {self.nm.content_size()})"
                )
            if self._is_file_unchanged(n):
                return 0, len(n.data), True
            nv = self.nm.get(n.id)
            if nv is not None and nv.offset != 0:
                try:
                    hdr = self.data_backend.read_at(nv.offset, NEEDLE_HEADER_SIZE)
                    cookie, _, _ = parse_needle_header(hdr)
                    if cookie != n.cookie:
                        raise VolumeError(f"mismatching cookie {n.cookie:x}")
                except VolumeError:
                    raise
                except Exception as e:
                    raise VolumeError(f"reading existing needle: {e}")
            n.append_at_ns = append_at_ns or time.time_ns()
            blob = n.to_bytes(self.version)
            if self.turbo is not None:
                if n.id == 0xFFFFFFFFFFFFFFFF:
                    # the native map's EMPTY_KEY slot sentinel: a record
                    # stored under it would vanish on the next table grow,
                    # so refuse loudly instead of acking a doomed write
                    raise VolumeError(
                        "key ffffffffffffffff is reserved on native-attached"
                        " volumes"
                    )
                # the native engine owns the append (dat + idx + map updated
                # atomically under its per-volume lock)
                offset = self.turbo.append(self.id, n.id, blob, n.size, False)
            else:
                offset = self.data_backend.append(blob)
                if nv is None or nv.offset < offset:
                    self.nm.put(n.id, offset, n.size)
            self.last_append_at_ns = n.append_at_ns
            if self.last_modified_ts_seconds < n.last_modified:
                self.last_modified_ts_seconds = n.last_modified
            if fsync:
                # sweedlint: ok blocking-under-lock write→fsync→ack ordering under the lock IS the durability contract (docs/CRASH.md)
                self.sync()
            return offset, n.size, False

    def _is_file_unchanged(self, n: Needle) -> bool:
        if str(self.ttl):
            return False
        # sweedlint: ok lock-discipline called with self._lock held by write_needle
        nv = self.nm.get(n.id)
        if nv is None or nv.offset == 0 or not size_is_valid(nv.size):
            return False
        try:
            # sweedlint: ok lock-discipline called with self._lock held by write_needle
            blob = self.data_backend.read_at(
                nv.offset, get_actual_size(nv.size, self.version)
            )
            old = Needle.from_bytes(blob, nv.size, self.version)
        except Exception:
            return False
        # (the reference also compares checksums — redundant given the data
        # bytes themselves match, and n.checksum isn't computed until encode)
        return old.cookie == n.cookie and old.data == n.data

    # -- delete path (volume_read_write.go:194-220) --------------------------
    def delete_needle(
        self, n: Needle, append_at_ns: Optional[int] = None
    ) -> int:
        """Returns the size of the deleted needle (0 if absent)."""
        with self._lock:
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read only")
            nv = self.nm.get(n.id)
            if nv is None or not size_is_valid(nv.size):
                return 0
            size = nv.size
            n.data = b""
            n.append_at_ns = append_at_ns or time.time_ns()
            blob = n.to_bytes(self.version)
            if self.turbo is not None:
                self.turbo.append(self.id, n.id, blob, 0, True)
            else:
                offset = self.data_backend.append(blob)
                self.nm.delete(n.id, offset)
            self.last_append_at_ns = n.append_at_ns
            return size

    # -- read path (volume_read_write.go:262-302) ----------------------------
    def read_needle(self, n: Needle, read_deleted: bool = False) -> int:
        with self._lock:
            nv = self.nm.get(n.id)
            if nv is None or nv.offset == 0:
                raise NotFoundError(f"needle {n.id:x} not found")
            read_size = nv.size
            if read_size < 0:  # IsDeleted (size 0 is a valid empty needle)
                if read_deleted and read_size != -1:
                    read_size = -read_size
                else:
                    raise DeletedError(f"needle {n.id:x} deleted")
            if read_size == 0:
                return 0
            blob = self.data_backend.read_at(
                nv.offset, get_actual_size(read_size, self.version)
            )
            m = Needle.from_bytes(blob, read_size, self.version)
            n.__dict__.update(m.__dict__)
        from .needle import FLAG_HAS_LAST_MODIFIED, FLAG_HAS_TTL

        if (
            not n.has(FLAG_HAS_TTL)
            or n.ttl.minutes() == 0
            or not n.has(FLAG_HAS_LAST_MODIFIED)
        ):
            return len(n.data)
        if time.time() < n.last_modified + n.ttl.minutes() * 60:
            return len(n.data)
        raise NotFoundError(f"needle {n.id:x} expired")

    def read_needle_extent(
        self, n: Needle, min_size: int = 0
    ) -> Optional[tuple]:
        """Zero-copy read setup: parse everything EXCEPT the data region.

        Returns ``(file, data_offset, data_len)`` where ``file`` is an
        independent dup of the .dat fd positioned nowhere in particular
        (the caller sendfiles from ``data_offset`` and must close it), or
        ``None`` when the record does not qualify — non-disk backend, v1
        layout, empty needle, below ``min_size``, or any parse
        irregularity — in which case the caller falls back to the
        buffered ``read_needle`` path, which also produces the proper
        error for corrupt records.

        NotFound/Deleted/expired raise exactly as ``read_needle`` does.
        ``n``'s metadata fields (cookie, flags, name, mime, ttl, …) are
        populated; ``n.data`` stays empty. The data CRC is NOT verified
        on this path (see docs/PARITY.md) — the bytes go straight from
        the page cache to the socket.
        """
        with self._lock:
            if self.version == 1:
                return None
            backend_fileno = getattr(self.data_backend, "fileno", None)
            if backend_fileno is None:
                return None
            nv = self.nm.get(n.id)
            if nv is None or nv.offset == 0:
                raise NotFoundError(f"needle {n.id:x} not found")
            read_size = nv.size
            if read_size < 0:
                raise DeletedError(f"needle {n.id:x} deleted")
            if read_size == 0:
                return None
            head = self.data_backend.read_at(nv.offset, NEEDLE_HEADER_SIZE + 4)
            if len(head) < NEEDLE_HEADER_SIZE + 4:
                return None
            m = Needle()
            m.parse_header(head[:NEEDLE_HEADER_SIZE])
            if m.size != read_size:
                return None  # buffered path raises the proper mismatch
            data_len = struct.unpack(">I", head[NEEDLE_HEADER_SIZE:])[0]
            if data_len < max(1, min_size):
                return None
            # tail = flags byte + optional name/mime/last_modified/ttl/pairs
            tail_len = read_size - 4 - data_len
            if tail_len < 1:
                return None
            tail = self.data_backend.read_at(
                nv.offset + NEEDLE_HEADER_SIZE + 4 + data_len, tail_len
            )
            if len(tail) < tail_len:
                return None
            # dup under the lock: a concurrent vacuum commit swaps
            # data_backend, and (nv.offset, fd) must come from the same
            # backend generation
            fd = os.dup(backend_fileno())
        try:
            # _read_body_v2 over a synthesized empty-data body parses the
            # flags/name/mime/last_modified/ttl/pairs tail with the exact
            # buffered-path logic
            m._read_body_v2(struct.pack(">I", 0) + tail)
        except Exception:
            os.close(fd)
            return None
        m.size = read_size
        n.__dict__.update(m.__dict__)
        n.data = b""
        from .needle import FLAG_HAS_LAST_MODIFIED, FLAG_HAS_TTL

        if (
            n.has(FLAG_HAS_TTL)
            and n.ttl.minutes() != 0
            and n.has(FLAG_HAS_LAST_MODIFIED)
            and time.time() >= n.last_modified + n.ttl.minutes() * 60
        ):
            os.close(fd)
            raise NotFoundError(f"needle {n.id:x} expired")
        f = os.fdopen(fd, "rb", buffering=0)
        return f, nv.offset + NEEDLE_HEADER_SIZE + 4, data_len

    # -- sequential scan (for rebuild/vacuum/export) -------------------------
    def scan_needles(
        self, verify_crc: bool = False
    ) -> Iterator[tuple[Needle, int, int]]:
        """Yield (needle, offset, total_len) for every record in the .dat."""
        # sweedlint: ok lock-discipline point-in-time scan; .dat is append-only below the snapshot size
        size = self.data_backend.size()
        # sweedlint: ok lock-discipline GIL-atomic reference read; only the locked compact commit replaces super_block
        offset = self.super_block.block_size()
        version = self.version
        while offset + NEEDLE_HEADER_SIZE <= size:
            # sweedlint: ok lock-discipline point-in-time scan; .dat is append-only below the snapshot size
            hdr = self.data_backend.read_at(offset, NEEDLE_HEADER_SIZE)
            if len(hdr) < NEEDLE_HEADER_SIZE:
                break
            cookie, nid, nsize = parse_needle_header(hdr)
            body_len = needle_body_length(nsize if nsize > 0 else 0, version)
            total = NEEDLE_HEADER_SIZE + body_len
            if offset + total > size:
                break
            n = Needle(cookie=cookie, id=nid, size=nsize)
            # sweedlint: ok lock-discipline point-in-time scan; .dat is append-only below the snapshot size
            body = self.data_backend.read_at(offset + NEEDLE_HEADER_SIZE, body_len)
            try:
                n.read_body_bytes(body, version)
            except Exception:
                if verify_crc:
                    raise
            yield n, offset, total
            offset += total

    # -- tail / backup (storage/volume_backup.go) ----------------------------
    def tail_needles(self, since_ns: int) -> Iterator[Needle]:
        """Records appended after since_ns, in append order — the incremental
        backup/follow stream (BackupVolume / VolumeTailSender). Tombstones
        appear as size-0 records; replay maps them to deletes."""
        for n, _, _ in self.scan_needles():
            if n.append_at_ns > since_ns:
                yield n

    # -- cloud tier (storage/volume_tier.go) ---------------------------------
    def tier_file(self) -> str:
        return self.file_name() + ".tier"

    def is_tiered(self) -> bool:
        """True when the .dat lives on a remote S3-class backend. Checked
        by type, not by a .tier stat — heartbeats call this per volume."""
        from .backend import RemoteS3File

        # sweedlint: ok lock-discipline benign racy read on the heartbeat path: a stale pointer misreports tier state for one beat; taking self._lock here would contend with the serving path
        return isinstance(self.data_backend, RemoteS3File)

    @staticmethod
    def _tier_credentials(info: dict) -> tuple[str, str, str]:
        """.tier descriptor → (endpoint, access_key, secret_key); named
        backends resolve through backend.toml, legacy descriptors carry
        creds inline."""
        if info.get("backend"):
            from .backend_config import resolve_backend

            bc = resolve_backend(info["backend"])
            return bc["endpoint"], bc["access_key"], bc["secret_key"]
        return (
            info.get("endpoint", ""),
            info.get("access_key", ""),
            info.get("secret_key", ""),
        )

    def tier_upload(
        self,
        endpoint: str = "",
        bucket: str = "",
        access_key: str = "",
        secret_key: str = "",
        keep_local: bool = False,
        skip_upload: bool = False,
        backend: str = "",
    ) -> dict:
        """Seal the volume and move its .dat to an S3-compatible backend,
        keeping .idx local; reads continue through ranged GETs
        (volume_tier.go + volume_grpc_tier_upload.go). With skip_upload a
        replica verifies the object another replica already uploaded and
        just writes its own .tier descriptor."""
        import json as _json

        from .backend import RemoteS3File, S3BackendStorage

        if backend:
            # the named backend is authoritative: the descriptor stores only
            # the NAME, so the upload must use exactly what a later reopen
            # will resolve — caller-supplied endpoint/creds are ignored
            from .backend_config import resolve_backend

            bc = resolve_backend(backend)
            endpoint = bc["endpoint"]
            access_key = bc["access_key"]
            secret_key = bc["secret_key"]
        if not endpoint:
            raise VolumeError("tier_upload needs -backend or an endpoint")
        self.detach_turbo()  # sealing moves the .dat off local disk
        with self._lock:
            was_read_only = self.read_only
            self.read_only = True
            try:
                # sweedlint: ok blocking-under-lock seal point: the upload snapshot must include every acked write
                self.data_backend.sync()
                key = f"{self.collection or 'default'}_{self.id}.dat"
                size = self.data_backend.size()
                local = self.file_name() + ".dat"
                s3 = S3BackendStorage(
                    endpoint, access_key, secret_key, name=backend
                )
                if skip_upload:
                    # sweedlint: ok blocking-under-lock admin-plane tier move on a sealed volume; the held lock is the exclusivity the backend swap needs
                    s3.verify_object(bucket, key, size)
                else:
                    # bounded memory: multipart for anything past one part
                    # sweedlint: ok blocking-under-lock admin-plane tier move on a sealed volume; the held lock is the exclusivity the backend swap needs
                    s3.upload_volume(bucket, key, local)
            except Exception:
                # the seal only sticks once the upload committed
                self.read_only = was_read_only
                raise
            info = {
                "bucket": bucket,
                "key": key,
                "size": size,
            }
            if backend:
                # descriptor names the backend; secrets stay in backend.toml
                info["backend"] = backend
            else:
                # legacy inline-creds flavor (0600): still supported so a
                # cluster without backend.toml keeps working, but secrets
                # land in every data dir — prefer -backend
                info.update(
                    endpoint=endpoint,
                    access_key=access_key,
                    secret_key=secret_key,
                )
            tf = self.tier_file()
            # atomic + durable: a crash mid-write must not leave a torn
            # .tier that poisons the next startup scan — either the old
            # state (no descriptor, .dat intact) or the new one exists
            from .commit import atomic_write

            # sweedlint: ok blocking-under-lock descriptor commit point must exclude writers; faultpoint sleeps are test-only
            faultpoints.fire("tier.upload.descriptor", path=local)
            # sweedlint: ok blocking-under-lock descriptor commit point must exclude writers (docs/CRASH.md)
            atomic_write(tf, _json.dumps(info).encode(), mode=0o600)
            # sweedlint: ok blocking-under-lock descriptor commit point must exclude writers; faultpoint sleeps are test-only
            faultpoints.fire("tier.upload.committed", path=tf)
            self.data_backend.close()
            # sweedlint: ok blocking-under-lock admin-plane tier move on a sealed volume; the held lock is the exclusivity the backend swap needs
            self.data_backend = RemoteS3File(
                endpoint, bucket, key, access_key, secret_key, size=size
            )
            if not keep_local:
                # sweedlint: ok durability past the .tier commit point; a crash leaves a harmless local copy
                os.unlink(local)
            # never echo credentials back to callers (the handler serializes
            # this dict into an HTTP response)
            return {
                k: v for k, v in info.items() if k not in ("access_key", "secret_key")
            }

    def tier_download(
        self, access_key: str = "", secret_key: str = ""
    ) -> None:
        """Fetch the .dat back from the remote tier (volume_grpc_tier_download.go)."""
        import json as _json

        from .backend import DiskFile, S3BackendStorage

        from .commit import StagedCommit

        with self._lock:
            with open(self.tier_file()) as f:
                info = _json.load(f)
            endpoint, ak, sk = self._tier_credentials(info)
            s3 = S3BackendStorage(
                endpoint, access_key or ak, secret_key or sk,
                name=info.get("backend", ""),
            )
            local = self.file_name() + ".dat"
            # two-phase: the fetched .dat stages as .tmp and the .tier
            # descriptor's removal rides the commit manifest, so a crash
            # anywhere leaves the volume either fully tiered (descriptor
            # intact, staged bytes GC'd at restart) or fully local
            sc = StagedCommit(self.file_name(), "tier.download")
            tmp = sc.stage(local)
            sc.remove_on_commit(self.tier_file())
            try:
                # ranged-GET pages straight to disk: no whole-volume buffer
                # sweedlint: ok blocking-under-lock admin-plane tier move on a sealed volume; the held lock is the exclusivity the backend swap needs
                got = s3.download_volume(info["bucket"], info["key"], tmp)
                # sweedlint: ok blocking-under-lock descriptor commit point must exclude writers; faultpoint sleeps are test-only
                faultpoints.fire("tier.download.fetched", path=tmp)
                if got != info["size"]:
                    raise VolumeError(
                        f"tier download: got {got} bytes, want {info['size']}"
                    )
                # sweedlint: ok blocking-under-lock two-phase commit point; exclusivity is the crash-safety contract
                sc.commit()
            except Exception:
                sc.abort()
                raise
            self.data_backend.close()
            self.data_backend = DiskFile(local)

    # -- vacuum / compaction (volume_vacuum.go) ------------------------------
    def compact(self, bytes_per_second: int = 0) -> None:
        """Concurrent compaction: snapshot-scan live needles to .cpd/.cpx
        WITHOUT the write lock, then take the lock only to replay the delta
        and swap files — the reference's `Compact2` + `makeupDiff`
        (`volume_vacuum.go:66,181`). Writes and deletes keep landing during
        the bulk copy; the commit replays every .idx entry appended after
        the snapshot point (puts copy the new needle bytes, tombstones
        re-delete), so no update is lost.

        Safe because both logs are append-only: bytes below the snapshot
        sizes are immutable, so the unlocked scan reads a consistent
        point-in-time state.

        `bytes_per_second` paces the unlocked bulk copy (the reference's
        compactionBytePerSecond throttle) so maintenance IO doesn't starve
        the data plane; 0 = unthrottled.
        """
        from . import idx as idx_mod
        from ..util.throttler import WriteThrottler
        from .types import needle_map_entry_size

        if self.turbo is not None:  # sweedlint: ok lock-discipline admin pre-check; the reattach ctx re-reads under the lock
            # compaction rewrites the .dat/.idx pair: take the data plane
            # back for the duration, re-attach over the compacted files
            with self._turbo_reattach_ctx():
                return self.compact(bytes_per_second)

        throttler = WriteThrottler(bytes_per_second)

        with self._lock:
            if self._is_compacting:
                raise VolumeError(f"volume {self.id} is already compacting")
            self._is_compacting = True
        base = self.file_name()
        entry_size = needle_map_entry_size(self.offset_size)
        version = self.version
        try:
            with self._lock:
                # sweedlint: ok blocking-under-lock snapshot point: the sizes below are only meaningful after a flush
                self.sync()
                snap_dat = self.data_backend.size()
                snap_idx = self.nm.index_file_size()
                sb = self.super_block
            new_sb = SuperBlock(
                version=version,
                replica_placement=sb.replica_placement,
                ttl=sb.ttl,
                compaction_revision=(sb.compaction_revision + 1) & 0xFFFF,
                extra=sb.extra,
            )
            # phase 1 (no lock): live map as of the snapshot, from the
            # immutable .idx prefix
            live: dict[int, tuple[int, int]] = {}
            with open(base + ".idx", "rb") as f:
                prefix = f.read(snap_idx)
            for i in range(0, len(prefix) - entry_size + 1, entry_size):
                key, off, size = idx_mod.unpack_entry(
                    prefix[i : i + entry_size], self.offset_size
                )
                if size_is_valid(size):
                    live[key] = (off, size)
                else:
                    live.pop(key, None)
            # phase 2 (no lock): copy live needles in .dat order up to the
            # snapshot size
            with open(base + ".cpd", "wb") as dst, open(
                base + ".cpx", "wb"
            ) as dst_idx:
                dst.write(new_sb.to_bytes())
                new_offset = new_sb.block_size()
                offset = sb.block_size()
                while offset + NEEDLE_HEADER_SIZE <= snap_dat:
                    # sweedlint: ok lock-discipline deliberate lock-free copy phase; bytes below snap_dat are immutable
                    hdr = self.data_backend.read_at(offset, NEEDLE_HEADER_SIZE)
                    if len(hdr) < NEEDLE_HEADER_SIZE:
                        break
                    _, nid, nsize = parse_needle_header(hdr)
                    body_len = needle_body_length(
                        nsize if nsize > 0 else 0, version
                    )
                    total = NEEDLE_HEADER_SIZE + body_len
                    if offset + total > snap_dat:
                        break
                    lv = live.get(nid)
                    if (
                        lv is not None
                        and lv[0] == offset
                        and size_is_valid(lv[1])
                    ):
                        faultpoints.fire("vacuum.copy", path=base + ".cpd")
                        # sweedlint: ok lock-discipline deliberate lock-free copy phase; bytes below snap_dat are immutable
                        dst.write(self.data_backend.read_at(offset, total))
                        dst_idx.write(
                            idx_mod.pack_entry(
                                nid, new_offset, nsize, self.offset_size
                            )
                        )
                        new_offset += total
                        throttler.maybe_slowdown(total)
                    offset += total
                # phase 3 (locked): makeupDiff — replay .idx entries
                # appended during phases 1-2, then swap
                with self._lock:
                    # sweedlint: ok blocking-under-lock makeupDiff snapshot: the .idx tail must be flushed before replay; writers are excluded on purpose
                    self.sync()
                    end_idx = self.nm.index_file_size()
                    if end_idx > snap_idx:
                        with open(base + ".idx", "rb") as f:
                            f.seek(snap_idx)
                            diff = f.read(end_idx - snap_idx)
                        for i in range(
                            0, len(diff) - entry_size + 1, entry_size
                        ):
                            key, off, size = idx_mod.unpack_entry(
                                diff[i : i + entry_size], self.offset_size
                            )
                            if size_is_valid(size):
                                total = NEEDLE_HEADER_SIZE + needle_body_length(
                                    size, version
                                )
                                dst.write(self.data_backend.read_at(off, total))
                                dst_idx.write(
                                    idx_mod.pack_entry(
                                        key, new_offset, size, self.offset_size
                                    )
                                )
                                new_offset += total
                            else:
                                # copy the TOMBSTONE NEEDLE itself (it sits
                                # at `off` in the old .dat) and point the
                                # idx entry at its new offset — a 0-offset
                                # tombstone would fail load-time integrity
                                # verification and be truncated away,
                                # resurrecting the delete
                                total = NEEDLE_HEADER_SIZE + needle_body_length(
                                    0, version
                                )
                                dst.write(self.data_backend.read_at(off, total))
                                dst_idx.write(
                                    idx_mod.pack_entry(
                                        key, new_offset, size, self.offset_size
                                    )
                                )
                                new_offset += total
                    # close before the rename-swap; the outer `with` close
                    # is then a no-op
                    dst.close()
                    dst_idx.close()
                    # sweedlint: ok blocking-under-lock compact commit swaps .dat/.idx and must exclude writers (docs/CRASH.md); faultpoint sleeps are test-only
                    self._commit_compact(base)
        finally:
            with self._lock:
                self._is_compacting = False

    # Compact2 IS the compaction here; alias kept for reference parity
    compact2 = compact

    def _commit_compact(self, base: str) -> None:
        """Atomic swap of the compacted pair. The naive two-rename commit
        had a crash window where the new .dat was live against the OLD .idx
        (every offset wrong); staging both renames behind one commit
        manifest makes the swap all-or-nothing across restarts
        (storage/commit.py)."""
        with self._lock:
            return self._commit_compact_locked(base)

    def _commit_compact_locked(self, base: str) -> None:
        from .commit import StagedCommit

        self.data_backend.close()
        self.nm.close()
        sc = StagedCommit(base, "vacuum")
        sc.stage(base + ".dat", tmp_path=base + ".cpd")
        sc.stage(base + ".idx", tmp_path=base + ".cpx")
        # sweedlint: ok blocking-under-lock compact commit swaps .dat/.idx; it must exclude writers (docs/CRASH.md)
        sc.commit()
        self.data_backend = DiskFile(base + ".dat")
        import struct as _struct

        head = self.data_backend.read_at(0, SUPER_BLOCK_SIZE)
        extra_size = _struct.unpack(">H", head[6:8])[0]
        self.super_block = SuperBlock.from_bytes(
            self.data_backend.read_at(0, SUPER_BLOCK_SIZE + extra_size)
        )
        idx_file = open(base + ".idx", "a+b", buffering=0)
        try:
            self.nm = self._load_needle_map(idx_file)
        except Exception:
            idx_file.close()
            raise

    # -- lifecycle -----------------------------------------------------------
    def sync(self) -> None:
        with self._lock:
            if self.turbo is not None:
                self.turbo.sync(self.id)
                return
            # sweedlint: ok blocking-under-lock Volume.sync IS the durability primitive; callers hold the lock for write→fsync→ack ordering
            self.data_backend.sync()
            self.nm.sync()

    def close(self) -> None:
        self.detach_turbo(reload_map=False)
        with self._lock:
            self.nm.close()
            self.data_backend.close()

    def destroy(self) -> None:
        """Remove every file of this volume (volume_read_write.go:46-72)."""
        with self._lock:
            if self._is_compacting:
                raise VolumeError(f"volume {self.id} is compacting")
            self.close()
            base = self.file_name()
            for ext in (".dat", ".idx", ".vif", ".sdx", ".cpd", ".cpx",
                        ".note", ".ldb", ".mdx", ".mdx.meta"):
                try:
                    # sweedlint: ok durability destroy path; deletion is the goal, FileNotFoundError makes re-runs idempotent
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass
