"""Named remote-backend configuration for cloud tiering.

The reference keeps S3 credentials in the master's `[storage.backend.s3.*]`
config and volumes reference backends by name (`backend/s3_backend`,
`volume_tier.go` — the .vif carries only the backend name + key). Round-1
stored credentials inline in every `.tier` descriptor; this module closes
that hole: descriptors carry `{"backend": "s3.default"}` and the secrets
live only in `backend.toml` (searched in ., ~/.seaweedfs_tpu,
/etc/seaweedfs — same paths as every other config, WEED_* env overrides
apply):

    [s3.default]
    endpoint = "https://s3.us-east-1.amazonaws.com"
    access_key = ""
    secret_key = ""
"""

from __future__ import annotations

from typing import Optional

from ..util.config import Configuration, load_configuration


class BackendConfigError(KeyError):
    pass


def resolve_backend(
    name: str, conf: Optional[Configuration] = None
) -> dict:
    """Backend name ("s3.default") → {endpoint, access_key, secret_key}."""
    conf = conf or load_configuration("backend")
    endpoint = conf.get(f"{name}.endpoint")
    if endpoint is None:
        raise BackendConfigError(
            f"backend {name!r} not defined in backend.toml "
            f"(searched {conf.path or 'standard paths'})"
        )
    return {
        "endpoint": endpoint,
        "access_key": conf.get(f"{name}.access_key", "") or "",
        "secret_key": conf.get(f"{name}.secret_key", "") or "",
    }
