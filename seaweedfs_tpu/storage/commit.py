"""Two-phase staged-file commit shared by EC encode, vacuum, and tier moves.

Every multi-file transition in the storage layer has the same shape: new
files are produced next to live ones, then a rename swap retires the old
state. A crash mid-swap used to leave a volume that is neither fully old
nor fully new (a partial EC shard set, a compacted .dat with the stale
.idx). This module makes the transition all-or-nothing, the way f4 treats
encode-and-retire as an atomic recoverable state change:

1. **stage** — every output is written to a sibling staging name
   (``<final>.tmp``; vacuum keeps its reference ``.cpd``/``.cpx`` names);
2. **harden** — each staged file is fsync'd;
3. **commit point** — a manifest (``<base>.commit``, JSON: staged files +
   their exact sizes + post-rename deletions) is written atomically
   (tmp + rename) and the directory is fsync'd;
4. **apply** — each staged file is renamed onto its final name;
5. **cleanup** — the manifest is unlinked, directory fsync'd again.

Crash before 3: the restart scan finds staged files with no manifest and
garbage-collects them — the OLD state is intact (rollback). Crash at or
after 3: the manifest exists, every staged file is known durable, and the
scan re-executes 4-5 (roll-forward); ``os.replace`` is idempotent, so a
half-applied rename pass completes cleanly. There is no reachable state
where the swap is half-applied after recovery runs.

:func:`recover_directory` is that restart scan; DiskLocation runs it
before loading any volume. Fault points named ``<tag>.staged`` /
``<tag>.manifest`` / ``<tag>.rename`` / ``<tag>.renamed`` fire at each
protocol step so the crash matrix can kill the process between every pair
of steps (util/faultpoints.py).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..util import faultpoints, glog

COMMIT_EXT = ".commit"
STAGING_SUFFIX = ".tmp"

# staging names recovery may garbage-collect when no manifest claims them:
# generic ``.tmp`` plus vacuum's reference-parity ``.cpd``/``.cpx`` pair
_ORPHAN_EXTS = (STAGING_SUFFIX, ".cpd", ".cpx")


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Make renames/unlinks in ``path`` durable. Some filesystems refuse
    O_RDONLY fsync on directories; a refusal degrades to the pre-commit
    behavior rather than failing the operation."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, mode: Optional[int] = None) -> None:
    """Single-file atomic durable write: tmp → fsync → rename → dir fsync.
    Readers see the old contents or the new, never a torn prefix."""
    tmp = path + STAGING_SUFFIX
    flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
    fd = os.open(tmp, flags, mode if mode is not None else 0o666)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


class StagedCommit:
    """One two-phase transition for one volume.

    ``base_path`` is the volume base (``<dir>/<collection>_<vid>``); the
    manifest lives at ``<base>.commit``. ``tag`` names the operation
    (``ec.encode``, ``vacuum``, ``tier.download``) and prefixes the fault
    points fired inside :meth:`commit`.
    """

    def __init__(self, base_path: str, tag: str):
        self.base_path = os.path.abspath(base_path)
        self.dir = os.path.dirname(self.base_path)
        self.manifest_path = self.base_path + COMMIT_EXT
        self.tag = tag
        self._files: dict[str, str] = {}  # final abs path -> staged abs path
        self._remove: list[str] = []

    def stage(self, final_path: str, tmp_path: Optional[str] = None) -> str:
        """Register an output; returns the staging path the caller must
        write. Default staging name is ``<final>.tmp``."""
        final_path = os.path.abspath(final_path)
        tmp_path = os.path.abspath(tmp_path or final_path + STAGING_SUFFIX)
        self._files[final_path] = tmp_path
        return tmp_path

    def remove_on_commit(self, path: str) -> None:
        """Unlink ``path`` after the rename pass (e.g. the ``.tier``
        descriptor once the downloaded ``.dat`` is back in place). Recorded
        in the manifest so roll-forward repeats it."""
        self._remove.append(os.path.abspath(path))

    def commit(self) -> None:
        """Steps 2-5. After this returns, the new state is durable; if the
        process dies inside, recover_directory finishes or undoes it."""
        first_staged = next(iter(self._files.values()), None)
        faultpoints.fire(self.tag + ".staged", path=first_staged)
        entries = {}
        for final, tmp in self._files.items():
            fsync_file(tmp)
            entries[os.path.basename(final)] = {
                "tmp": os.path.basename(tmp),
                "size": os.path.getsize(tmp),
            }
        manifest = {
            "tag": self.tag,
            "files": entries,
            "remove": [os.path.basename(p) for p in self._remove],
        }
        atomic_write(
            self.manifest_path, json.dumps(manifest, indent=1).encode()
        )
        # -- the commit point: the manifest is durable -----------------------
        faultpoints.fire(self.tag + ".manifest", path=self.manifest_path)
        _apply_manifest(self.manifest_path, manifest, fault_tag=self.tag)

    def abort(self) -> None:
        """Drop staged files (in-process failure before/inside commit)."""
        for tmp in self._files.values():
            try:
                os.unlink(tmp)
            except OSError:
                pass
        for p in (self.manifest_path + STAGING_SUFFIX, self.manifest_path):
            try:
                os.unlink(p)
            except OSError:
                pass


def _apply_manifest(manifest_path: str, manifest: dict,
                    fault_tag: Optional[str] = None) -> None:
    """Steps 4-5, shared by the live commit and restart roll-forward.
    Renames are applied in sorted final-name order so a crash mid-pass is
    reproducible for the crash matrix."""
    directory = os.path.dirname(os.path.abspath(manifest_path))
    files = manifest.get("files", {})
    first = True
    for final_name in sorted(files):
        tmp = os.path.join(directory, files[final_name]["tmp"])
        final = os.path.join(directory, final_name)
        if os.path.exists(tmp):
            os.replace(tmp, final)
        if first and fault_tag:
            faultpoints.fire(fault_tag + ".rename")
            first = False
    fsync_dir(directory)
    if fault_tag:
        faultpoints.fire(fault_tag + ".renamed")
    for name in manifest.get("remove", []):
        try:
            os.unlink(os.path.join(directory, name))
        except FileNotFoundError:
            pass
    os.unlink(manifest_path)
    fsync_dir(directory)


def _manifest_complete(manifest_path: str, manifest: dict) -> bool:
    """Roll-forward precondition: every listed output exists — staged at
    its recorded size, or already renamed into place. fsync-before-manifest
    ordering makes this always true after a genuine crash; a False answer
    means the manifest is lying (torn by filesystem loss or hand-edited)
    and rolling forward would install short files."""
    directory = os.path.dirname(os.path.abspath(manifest_path))
    for final_name, ent in manifest.get("files", {}).items():
        tmp = os.path.join(directory, ent["tmp"])
        final = os.path.join(directory, final_name)
        want = ent.get("size", -1)
        if os.path.exists(tmp) and os.path.getsize(tmp) == want:
            continue
        if os.path.exists(final) and os.path.getsize(final) == want:
            continue
        return False
    return True


def recover_directory(directory: str) -> dict:
    """Startup recovery scan (step 0 of every DiskLocation load).

    - each ``*.commit`` manifest: roll the transition forward when every
      staged output is complete, otherwise garbage-collect its staged
      files and the manifest (the old state is still live);
    - any remaining orphan staging file (``.tmp``/``.cpd``/``.cpx``) is
      from a transition that died before its commit point: deleted.

    Returns ``{"rolled_forward": [...], "rolled_back": [...], "gc": [...]}``
    naming what was done (tests assert on it; callers log it). Idempotent —
    a crash during recovery itself re-runs cleanly.
    """
    actions: dict = {"rolled_forward": [], "rolled_back": [], "gc": []}
    if not os.path.isdir(directory):
        return actions
    entries = sorted(os.listdir(directory))
    for entry in entries:
        if not entry.endswith(COMMIT_EXT):
            continue
        manifest_path = os.path.join(directory, entry)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            files = manifest["files"]
            assert isinstance(files, dict)
        except Exception:
            # torn/garbage manifest: it never became a commit point
            _rollback(manifest_path, {}, actions)
            continue
        tag = manifest.get("tag", "?")
        if _manifest_complete(manifest_path, manifest):
            _apply_manifest(manifest_path, manifest)
            actions["rolled_forward"].append(f"{tag}:{entry}")
        else:
            glog.error(
                "commit manifest %s incomplete on disk; rolling back", entry
            )
            _rollback(manifest_path, manifest, actions)
            actions["rolled_back"].append(f"{tag}:{entry}")
    # orphan staging files: no manifest claimed them, so their transition
    # never committed — the live state never referenced them
    for entry in sorted(os.listdir(directory)):
        if entry.endswith(_ORPHAN_EXTS):
            path = os.path.join(directory, entry)
            try:
                os.unlink(path)
                actions["gc"].append(entry)
            except OSError:
                pass
    if actions["gc"] or actions["rolled_forward"] or actions["rolled_back"]:
        fsync_dir(directory)
    return actions


def _rollback(manifest_path: str, manifest: dict, actions: dict) -> None:
    directory = os.path.dirname(os.path.abspath(manifest_path))
    for ent in manifest.get("files", {}).values():
        tmp = os.path.join(directory, ent.get("tmp", ""))
        try:
            os.unlink(tmp)
            actions["gc"].append(os.path.basename(tmp))
        except OSError:
            pass
    try:
        os.unlink(manifest_path)
    except OSError:
        pass


def pending_commit(base_path: str) -> bool:
    """True while ``base_path`` has an unresolved commit manifest — the
    volume must not be (re)mounted until recovery resolves it."""
    return os.path.exists(base_path + COMMIT_EXT)
