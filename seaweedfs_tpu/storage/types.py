"""Core on-disk scalar types: needle ids, cookies, sizes, aligned offsets.

Byte layouts match the reference (`weed/storage/types/needle_types.go:33-40`,
`offset_4bytes.go`, `offset_5bytes.go`, `needle_id_type.go`):

- NeedleId: uint64, big-endian on disk (8 bytes)
- Cookie:   uint32, big-endian (4 bytes)
- Size:     int32 stored as uint32 big-endian; negative values (and the
  special TOMBSTONE -1) mark deletions
- Offset:   byte offset / 8 (NeedlePaddingSize alignment), stored as 4 bytes
  big-endian (default build, 32 GB max volume) or 5 bytes with the
  "5BytesOffset" flavor (the 5th byte is the *most* significant and is
  appended after the low 4 — matching `offset_5bytes.go:17-25`)
"""

from __future__ import annotations

import struct

# -- constants (weed/storage/types/needle_types.go:33-40) --------------------
COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_FILE_SIZE = -1

# Default build flavor: 4-byte offsets, 32GB max volume
# (weed/storage/types/offset_4bytes.go:13-15). The 5-byte flavor
# (offset_5bytes.go) raises the cap to 8 EB; both are supported here via the
# ``offset_size`` parameter.
OFFSET_SIZE_4 = 4
OFFSET_SIZE_5 = 5
OFFSET_SIZE = OFFSET_SIZE_4
MAX_POSSIBLE_VOLUME_SIZE_4 = 4 * 1024 * 1024 * 1024 * 8  # 32 GB
MAX_POSSIBLE_VOLUME_SIZE_5 = MAX_POSSIBLE_VOLUME_SIZE_4 * 256

NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16


def needle_map_entry_size(offset_size: int = OFFSET_SIZE) -> int:
    return NEEDLE_ID_SIZE + offset_size + SIZE_SIZE


def max_possible_volume_size(offset_size: int = OFFSET_SIZE) -> int:
    return (
        MAX_POSSIBLE_VOLUME_SIZE_5
        if offset_size == OFFSET_SIZE_5
        else MAX_POSSIBLE_VOLUME_SIZE_4
    )


# -- size helpers (weed/storage/types/needle_types.go:17-23) -----------------
def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def size_to_bytes(size: int) -> bytes:
    """int32 size → 4 bytes big-endian (two's complement for tombstones)."""
    return struct.pack(">I", size & 0xFFFFFFFF)


def bytes_to_size(b: bytes) -> int:
    """4 bytes big-endian → signed int32."""
    return struct.unpack(">i", b[:4])[0]


# -- needle id / cookie ------------------------------------------------------
def needle_id_to_bytes(needle_id: int) -> bytes:
    return struct.pack(">Q", needle_id)


def bytes_to_needle_id(b: bytes) -> int:
    return struct.unpack(">Q", b[:8])[0]


def cookie_to_bytes(cookie: int) -> bytes:
    return struct.pack(">I", cookie)


def bytes_to_cookie(b: bytes) -> int:
    return struct.unpack(">I", b[:4])[0]


def parse_needle_id(s: str) -> int:
    """Hex string → needle id (weed/storage/types/needle_id_type.go:40-46)."""
    v = int(s, 16)
    if v < 0 or v > 0xFFFFFFFFFFFFFFFF:
        raise ValueError(f"needle id {s} out of range")
    return v


def parse_cookie(s: str) -> int:
    """Hex string → cookie (weed/storage/types/needle_types.go:55-61)."""
    v = int(s, 16)
    if v < 0 or v > 0xFFFFFFFF:
        raise ValueError(f"cookie {s} out of range")
    return v


# -- offsets -----------------------------------------------------------------
# Offsets are stored divided by NEEDLE_PADDING_SIZE (all needle records are
# 8-byte aligned). The 4-byte encoding is plain big-endian uint32 of the
# scaled value; the 5-byte encoding appends the most-significant 5th byte
# AFTER the big-endian low 4 (weed/storage/types/offset_5bytes.go:17-25).

def offset_to_bytes(actual_offset: int, offset_size: int = OFFSET_SIZE) -> bytes:
    if actual_offset % NEEDLE_PADDING_SIZE != 0:
        raise ValueError(f"offset {actual_offset} not {NEEDLE_PADDING_SIZE}-aligned")
    scaled = actual_offset // NEEDLE_PADDING_SIZE
    if offset_size == OFFSET_SIZE_4:
        if scaled > 0xFFFFFFFF:
            raise ValueError(f"offset {actual_offset} exceeds 32GB volume cap")
        return struct.pack(">I", scaled)
    low = struct.pack(">I", scaled & 0xFFFFFFFF)
    b4 = (scaled >> 32) & 0xFF
    if scaled >> 40:
        raise ValueError(f"offset {actual_offset} exceeds 5-byte offset cap")
    return low + bytes([b4])


def bytes_to_offset(b: bytes, offset_size: int = OFFSET_SIZE) -> int:
    """Stored offset bytes → actual byte offset (already ×8)."""
    scaled = struct.unpack(">I", b[:4])[0]
    if offset_size == OFFSET_SIZE_5:
        scaled |= b[4] << 32
    return scaled * NEEDLE_PADDING_SIZE


def offset_is_zero(b: bytes) -> bool:
    return all(x == 0 for x in b)
