"""S3-class cold-tier backend (backend/s3_backend/s3_backend.go).

Two layers, mirroring the reference split:

``S3BackendStorage``
    The per-backend handle (s3_backend.go:30 S3BackendStorage): endpoint +
    credentials resolved once — from ``backend.toml`` for a named backend
    or passed inline — plus the whole-object verbs the tier moves need:
    ``upload_volume`` (bounded-memory multipart PUT), ``download_volume``
    (ranged-GET paging straight to disk), ``verify_object`` (HEAD +
    size check, for replicas skipping a redundant upload) and
    ``delete_object``. The lifecycle controller and ``Volume.tier_upload``
    both drive tier moves through this class so the upload a replica
    verifies is exactly what a later reopen will resolve.

``RemoteS3File``
    The sealed volume's read handle (s3_backend.go:117 S3BackendStorageFile):
    a ``BackendStorageFile`` whose ``read_at`` is a ranged GET and whose
    size comes from HEAD; writes raise — tiered volumes are sealed.

Tests and the lifecycle probe point these at ``fake_s3.FakeS3Server``, a
directory-backed S3 stand-in in this package.
"""

from __future__ import annotations

from ...util.parsers import tolerant_uint
from .core import BackendStorageFile


class S3BackendStorage:
    """One configured S3-compatible backend (named or inline-credential)."""

    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        name: str = "",
    ):
        from ...s3api.s3_client import S3Client

        if not endpoint:
            raise ValueError("S3BackendStorage needs an endpoint")
        self.name = name
        self.endpoint = endpoint
        self.access_key = access_key
        self.secret_key = secret_key
        self.client = S3Client(endpoint, access_key, secret_key)

    @classmethod
    def from_config(cls, name: str) -> "S3BackendStorage":
        """Resolve a named backend ("s3.default") through backend.toml —
        the only flavor whose .tier descriptors stay secret-free."""
        from ..backend_config import resolve_backend

        bc = resolve_backend(name)
        return cls(
            bc["endpoint"], bc["access_key"], bc["secret_key"], name=name
        )

    # -- whole-object verbs for tier moves -----------------------------------
    def upload_volume(self, bucket: str, key: str, path: str) -> int:
        """Upload a sealed .dat with bounded memory (multipart past one
        part); idempotent — re-uploading the same sealed bytes after a
        crash overwrites with identical content. Returns the size."""
        import os as _os

        self.client.create_bucket(bucket)  # idempotent-ish; 409 is fine
        status = self.client.put_object_from_file(bucket, key, path)
        if status != 200:
            raise IOError(f"tier upload {bucket}/{key}: HTTP {status}")
        return _os.path.getsize(path)

    def verify_object(self, bucket: str, key: str, size: int) -> None:
        """HEAD + size check: a replica that skips the redundant upload
        still proves the object its descriptor will point at exists."""
        status, _, headers = self.client.head_object(bucket, key)
        if status != 200:
            raise IOError(f"tier object {bucket}/{key} missing: HTTP {status}")
        # tolerant: a missing/garbage header yields -1 → size-mismatch error
        remote_size = tolerant_uint(headers.get("Content-Length", -1), -1)
        if remote_size != size:
            raise IOError(
                f"tier object {bucket}/{key} size {remote_size} != local {size}"
            )

    def download_volume(self, bucket: str, key: str, path: str) -> int:
        """Ranged-GET the object back to a local path; returns bytes."""
        return self.client.get_object_to_file(bucket, key, path)

    def delete_object(self, bucket: str, key: str) -> None:
        self.client.delete_object(bucket, key)

    def new_storage_file(
        self, bucket: str, key: str, size: int = -1
    ) -> "RemoteS3File":
        return RemoteS3File(
            self.endpoint, bucket, key, self.access_key, self.secret_key,
            size=size,
        )


class RemoteS3File(BackendStorageFile):
    """Read-only .dat served from an S3-compatible endpoint via ranged GETs
    (backend/s3_backend/s3_backend.go:33,117,152: ReadAt → ranged GET,
    size from HEAD). Writes are invalid — tiered volumes are sealed."""

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        key: str,
        access_key: str = "",
        secret_key: str = "",
        size: int = -1,
    ):
        from ...s3api.s3_client import S3Client

        self.client = S3Client(endpoint, access_key, secret_key)
        self.bucket, self.key = bucket, key
        self._size = size
        if self._size < 0:
            status, _, headers = self.client.head_object(bucket, key)
            if status != 200:
                raise FileNotFoundError(f"s3://{bucket}/{key}: HTTP {status}")
            self._size = tolerant_uint(headers.get("Content-Length", 0), 0)

    def read_at(self, offset: int, size: int) -> bytes:
        if size <= 0 or offset >= self._size:
            return b""
        end = min(offset + size, self._size) - 1
        status, data, _ = self.client.get_object(
            self.bucket, self.key, rng=f"bytes={offset}-{end}"
        )
        if status not in (200, 206):
            raise IOError(f"s3 ranged read {self.key}@{offset}: HTTP {status}")
        return data

    def write_at(self, offset: int, data: bytes) -> int:
        raise IOError("remote-tier volume is read only")

    def append(self, data: bytes) -> int:
        raise IOError("remote-tier volume is read only")

    def truncate(self, size: int) -> None:
        raise IOError("remote-tier volume is read only")

    def size(self) -> int:
        return self._size

    def name(self) -> str:
        return f"s3://{self.bucket}/{self.key}"

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass
