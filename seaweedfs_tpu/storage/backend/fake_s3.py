"""Directory-backed fake-S3 server for tier tests and the lifecycle probe.

The reference tests its s3_backend against localstack-style stand-ins; this
is the minimal equivalent: just enough of the S3 REST surface for
``S3BackendStorage`` / ``RemoteS3File`` (and the underlying
``s3api.s3_client.S3Client``) to tier volumes against it —

    PUT    /bucket                      create bucket
    PUT    /bucket/key                  put object
    POST   /bucket/key?uploads          initiate multipart → UploadId
    PUT    /bucket/key?partNumber&uploadId   upload part
    POST   /bucket/key?uploadId         complete multipart (concatenate)
    DELETE /bucket/key?uploadId         abort multipart
    GET    /bucket/key [Range: bytes=a-b]    (ranged) get object
    HEAD   /bucket/key                  size probe
    DELETE /bucket[/key]                delete

Objects live as plain files under ``root/bucket/key`` so tests can corrupt
or inspect the cold tier directly. SigV4 Authorization headers are accepted
and ignored — signing is the client's concern; this server only fakes
storage semantics. Deliberately NOT the full ``s3api.S3ApiServer`` (which
needs a filer): the cold tier must be mountable in a unit test with nothing
else running.
"""

from __future__ import annotations

import os
import re
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ...util.parsers import parse_ascii_uint, tolerant_uint


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "FakeS3/0.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    # -- helpers -------------------------------------------------------------
    def _split(self):
        parsed = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        parts = parsed.path.strip("/").split("/", 1)
        bucket = parts[0] if parts[0] else ""
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key, q

    def _obj_path(self, bucket: str, key: str) -> str:
        # keys stay flat (collection_vid.dat); refuse traversal outright
        safe = key.replace("/", "_").replace("..", "_")
        return os.path.join(self.server.root, bucket, safe)

    def _reply(self, status: int, body: bytes = b"", headers=None):
        self.send_response(status)
        hdrs = dict(headers or {})
        for k, v in hdrs.items():
            self.send_header(k, v)
        # HEAD advertises the object size explicitly; emitting a second
        # Content-Length (the empty body's 0) makes strict clients see a
        # joined "N, 0" header and mis-size the download
        if not any(k.lower() == "content-length" for k in hdrs):
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD" and body:
            self.wfile.write(body)

    def _read_body(self) -> bytes:
        n = tolerant_uint(self.headers.get("Content-Length", "0"), 0)
        body = self.rfile.read(n) if n else b""
        # aws-chunked framing (streaming SigV4): strip the chunk envelope
        if b";chunk-signature=" in body[:200]:
            out, rest = bytearray(), body
            while rest:
                head, _, rest = rest.partition(b"\r\n")
                size = int(head.split(b";")[0], 16)
                if size == 0:
                    break
                out += rest[:size]
                rest = rest[size + 2:]
            return bytes(out)
        return body

    # -- verbs ---------------------------------------------------------------
    def do_PUT(self):
        bucket, key, q = self._split()
        if not bucket:
            return self._reply(400)
        bdir = os.path.join(self.server.root, bucket)
        if not key:  # create bucket
            os.makedirs(bdir, exist_ok=True)
            return self._reply(200)
        if not os.path.isdir(bdir):
            return self._reply(404, b"<Error><Code>NoSuchBucket</Code></Error>")
        body = self._read_body()
        if "partNumber" in q and "uploadId" in q:
            try:
                pn = parse_ascii_uint(q["partNumber"])
            except ValueError:
                return self._reply(400)
            with self.server.lock:
                parts = self.server.uploads.get(q["uploadId"])
                if parts is None:
                    return self._reply(404)
                parts[pn] = body
            return self._reply(200, headers={"ETag": f'"{len(body):x}"'})
        with open(self._obj_path(bucket, key), "wb") as f:
            f.write(body)
        return self._reply(200, headers={"ETag": '"fake"'})

    def do_POST(self):
        bucket, key, q = self._split()
        if "uploads" in q:  # initiate multipart
            uid = uuid.uuid4().hex
            with self.server.lock:
                self.server.uploads[uid] = {}
                self.server.upload_keys[uid] = (bucket, key)
            return self._reply(
                200, f"<InitiateMultipartUploadResult><UploadId>{uid}"
                     f"</UploadId></InitiateMultipartUploadResult>".encode())
        if "uploadId" in q:  # complete multipart
            self._read_body()
            with self.server.lock:
                parts = self.server.uploads.pop(q["uploadId"], None)
                self.server.upload_keys.pop(q["uploadId"], None)
            if parts is None:
                return self._reply(404)
            with open(self._obj_path(bucket, key), "wb") as f:
                for num in sorted(parts):
                    f.write(parts[num])
            return self._reply(
                200, b"<CompleteMultipartUploadResult/>")
        return self._reply(400)

    def do_GET(self):
        bucket, key, _ = self._split()
        if not bucket:
            return self._reply(200, b"<ListAllMyBucketsResult/>")
        path = self._obj_path(bucket, key) if key else ""
        if not key or not os.path.isfile(path):
            return self._reply(404, b"<Error><Code>NoSuchKey</Code></Error>")
        size = os.path.getsize(path)
        rng = self.headers.get("Range", "")
        m = re.match(r"bytes=(\d+)-(\d*)$", rng)
        with open(path, "rb") as f:
            if m:
                start = int(m.group(1))
                end = int(m.group(2)) if m.group(2) else size - 1
                end = min(end, size - 1)
                if start > end:
                    return self._reply(416)
                f.seek(start)
                data = f.read(end - start + 1)
                return self._reply(206, data, headers={
                    "Content-Range": f"bytes {start}-{end}/{size}",
                })
            return self._reply(200, f.read())

    def do_HEAD(self):
        bucket, key, _ = self._split()
        path = self._obj_path(bucket, key) if bucket and key else ""
        if not path or not os.path.isfile(path):
            return self._reply(404)
        return self._reply(
            200, headers={"Content-Length": str(os.path.getsize(path))}
        )

    def do_DELETE(self):
        bucket, key, q = self._split()
        if "uploadId" in q:  # abort multipart
            with self.server.lock:
                self.server.uploads.pop(q["uploadId"], None)
                self.server.upload_keys.pop(q["uploadId"], None)
            return self._reply(204)
        if bucket and key:
            try:
                # sweedlint: ok durability fake-S3 object store under the test root, not the volume data plane; S3 DeleteObject has no staged-commit semantics to preserve
                os.unlink(self._obj_path(bucket, key))
            except FileNotFoundError:
                pass
            return self._reply(204)
        if bucket:
            import shutil

            shutil.rmtree(
                os.path.join(self.server.root, bucket), ignore_errors=True
            )
            return self._reply(204)
        return self._reply(400)


class FakeS3Server:
    """``with FakeS3Server(root) as s3: ... s3.endpoint ...``"""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        os.makedirs(root, exist_ok=True)
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.root = root
        self._srv.lock = threading.Lock()
        self._srv.uploads = {}       # uploadId → {partNumber: bytes}
        self._srv.upload_keys = {}   # uploadId → (bucket, key)
        self._thread: threading.Thread | None = None
        self.root = root
        self.host, self.port = self._srv.server_address[:2]
        self.endpoint = f"http://{self.host}:{self.port}"

    def start(self) -> "FakeS3Server":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self) -> "FakeS3Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def object_path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, bucket, key.replace("/", "_"))

    def bytes_stored(self) -> int:
        """Total object bytes on the fake backend (probe tier accounting)."""
        total = 0
        for dirpath, _, files in os.walk(self.root):
            for fn in files:
                total += os.path.getsize(os.path.join(dirpath, fn))
        return total
