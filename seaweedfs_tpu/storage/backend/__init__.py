"""Backend storage package: local + remote IO under a volume's .dat.

Split along the reference's `weed/storage/backend/` layout:
    core.py        BackendStorageFile / DiskFile / MemoryFile
    s3_backend.py  S3BackendStorage + RemoteS3File (the cold tier)
    fake_s3.py     directory-backed fake-S3 server for tests/probes

The historical import surface (`from ..storage.backend import DiskFile`)
is preserved here.
"""

from .core import BackendStorageFile, DiskFile, MemoryFile
from .s3_backend import RemoteS3File, S3BackendStorage

__all__ = [
    "BackendStorageFile",
    "DiskFile",
    "MemoryFile",
    "RemoteS3File",
    "S3BackendStorage",
]
