"""Backend storage files: the IO abstraction under a volume's .dat.

Mirrors `weed/storage/backend/backend.go:15-25` (BackendStorageFile):
read_at/write_at/truncate/close/size/name/sync. DiskFile wraps a local file;
MemoryFile supports tests and scratch volumes. The remote S3 tier lives in
the sibling module (s3_backend.py — backend/s3_backend/s3_backend.go).
"""

from __future__ import annotations

import os
import threading

from ...util.locks import make_lock


class BackendStorageFile:
    def read_at(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def write_at(self, offset: int, data: bytes) -> int:
        raise NotImplementedError

    def append(self, data: bytes) -> int:
        """Write at current end; returns the offset written at."""
        end = self.size()
        self.write_at(end, data)
        return end

    def truncate(self, size: int) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def name(self) -> str:
        raise NotImplementedError

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


class DiskFile(BackendStorageFile):
    """Local file with positional IO (backend/disk_file.go)."""

    def __init__(self, path: str, create: bool = False):
        self._path = path
        mode = "r+b" if os.path.exists(path) else ("w+b" if create else None)
        if mode is None:
            raise FileNotFoundError(path)
        # unbuffered: every write() reaches the kernel before we ack, like
        # Go's os.File — a kill -9 must not lose acknowledged needles
        # (durability against power loss still needs fsync=true / sync())
        self._f = open(path, mode, buffering=0)
        self._lock = make_lock("DiskFile._lock")

    def read_at(self, offset: int, size: int) -> bytes:
        # raw FileIO read/write are single syscalls and may be partial —
        # loop until done (BufferedIO used to do this for us)
        with self._lock:
            self._f.seek(offset)
            chunks = []
            remaining = size
            while remaining > 0:
                b = self._f.read(remaining)
                if not b:
                    break  # EOF
                chunks.append(b)
                remaining -= len(b)
            return b"".join(chunks)

    def write_at(self, offset: int, data: bytes) -> int:
        with self._lock:
            self._f.seek(offset)
            view = memoryview(data)
            written = 0
            while written < len(data):
                n = self._f.write(view[written:])
                if not n:
                    raise OSError(
                        f"short write at {offset + written} in {self._path}"
                    )
                written += n
            return written

    def truncate(self, size: int) -> None:
        with self._lock:
            self._f.truncate(size)

    def size(self) -> int:
        with self._lock:
            self._f.flush()
            return os.fstat(self._f.fileno()).st_size

    def name(self) -> str:
        return self._path

    def fileno(self) -> int:
        """Raw fd for the zero-copy (sendfile) read path; callers dup it
        under the volume lock before handing it to a socket relay."""
        return self._f.fileno()

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            # sweedlint: ok blocking-under-lock per-fd leaf lock serializing write+fsync; nothing nests inside it
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class MemoryFile(BackendStorageFile):
    """In-memory backend (tests; analog of backend/memory_map)."""

    def __init__(self, name: str = "<memory>"):
        self._buf = bytearray()
        self._name = name
        self._lock = make_lock("MemoryFile._lock")

    def read_at(self, offset: int, size: int) -> bytes:
        with self._lock:
            return bytes(self._buf[offset : offset + size])

    def write_at(self, offset: int, data: bytes) -> int:
        with self._lock:
            end = offset + len(data)
            if end > len(self._buf):
                self._buf.extend(b"\x00" * (end - len(self._buf)))
            self._buf[offset:end] = data
            return len(data)

    def truncate(self, size: int) -> None:
        with self._lock:
            del self._buf[size:]

    def size(self) -> int:
        with self._lock:
            return len(self._buf)

    def name(self) -> str:
        return self._name
