"""DiskLocation: one data directory holding volumes and EC shards.

Mirrors `weed/storage/disk_location.go` (+ `disk_location_ec.go`): scans the
directory on startup, loads every `<collection>_<vid>.dat` / `<vid>.dat`
volume and every `.ecx`-bearing EC volume, and watches free space to flip
volumes read-only (CheckDiskSpace, disk_location.go:314).
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Optional

from ..ec.ec_volume import EcShardsError, EcVolume
from .volume import Volume
from ..util.locks import make_rlock


def parse_volume_base_name(name: str) -> tuple[str, int]:
    """'col_3' → ('col', 3); '3' → ('', 3). Raises on non-volume names."""
    if "_" in name:
        collection, vid_str = name.rsplit("_", 1)
    else:
        collection, vid_str = "", name
    return collection, int(vid_str)


class DiskLocation:
    def __init__(
        self,
        directory: str,
        max_volume_count: int = 7,
        min_free_space_ratio: float = 0.01,
        needle_map_kind: str = "dense",
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.min_free_space_ratio = min_free_space_ratio
        self.needle_map_kind = needle_map_kind
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        self._lock = make_rlock("DiskLocation._lock")
        self._recovered = False

    # -- startup loading (disk_location.go:104-160) --------------------------
    def load_existing_volumes(self) -> None:
        with self._lock:
            # sweedlint: ok blocking-under-lock mount-time recovery; the location lock is uncontended until the scan returns
            self._recover_staged_commits()
            for entry in sorted(os.listdir(self.directory)):
                path = os.path.join(self.directory, entry)
                if not os.path.isfile(path):
                    continue
                base, ext = os.path.splitext(entry)
                try:
                    # .tier marks a sealed volume whose .dat moved to a
                    # remote backend — discover it like a local one
                    if ext in (".dat", ".tier"):
                        collection, vid = parse_volume_base_name(base)
                        if vid not in self.volumes:
                            # sweedlint: ok blocking-under-lock mount-time scan; a remote-tier volume probes its backend during open
                            self.volumes[vid] = Volume(
                                self.directory, collection, vid,
                                create_if_missing=False,
                                needle_map_kind=self.needle_map_kind,
                            )
                    elif ext == ".ecx":
                        collection, vid = parse_volume_base_name(base)
                        if vid not in self.ec_volumes:
                            ev = EcVolume(self.directory, collection, vid)
                            if ev.shards:
                                self.ec_volumes[vid] = ev
                            else:
                                ev.close()
                except EcShardsError as e:
                    # torn shard set (size mismatch / pending commit): the
                    # plain volume, if any, still serves; never mount a
                    # half-consistent EC view
                    from ..util import glog

                    glog.error("not mounting ec volume %s: %s", base, e)
                    continue
                except (ValueError, FileNotFoundError):
                    continue  # not a volume file
                except KeyError as e:
                    # e.g. a named tier backend missing from backend.toml —
                    # skip that volume, don't take the whole server down
                    from ..util import glog

                    glog.error("skipping volume %s: %s", base, e)
                    continue

    def _recover_staged_commits(self) -> None:
        """ONCE per process, resolve interrupted two-phase commits BEFORE
        any volume loads. Startup-only on purpose: load_existing_volumes is
        also re-run by runtime mount requests, and a re-scan then could
        garbage-collect the staging files of a compaction or encode that is
        legitimately in flight.

        Roll-forward/rollback semantics:
        staged transitions with a durable manifest roll forward (the EC
        shard set / compacted files / downloaded .dat take their final
        names), everything else is garbage-collected so the prior state
        serves untouched (storage/commit.py). A tier download's .tier
        descriptor removal rides the manifest's remove-list, so roll-forward
        covers it too."""
        if self._recovered:
            return
        self._recovered = True
        from ..util import glog
        from .commit import recover_directory

        actions = recover_directory(self.directory)
        for kind in ("rolled_forward", "rolled_back"):
            for item in actions[kind]:
                glog.info("startup recovery: %s %s", kind, item)
        if actions["gc"]:
            glog.info(
                "startup recovery: garbage-collected %d staged file(s): %s",
                len(actions["gc"]), ", ".join(actions["gc"]),
            )

    # -- volume management ---------------------------------------------------
    def add_volume(self, volume: Volume) -> None:
        with self._lock:
            self.volumes[volume.id] = volume

    def find_volume(self, vid: int) -> Optional[Volume]:
        return self.volumes.get(vid)

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        return self.ec_volumes.get(vid)

    def unload_volume(self, vid: int) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            v.close()
            return True

    def delete_volume(self, vid: int) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            v.destroy()
            return True

    def unload_ec_volume(self, vid: int) -> bool:
        with self._lock:
            ev = self.ec_volumes.pop(vid, None)
            if ev is None:
                return False
            ev.close()
            return True

    def volume_count(self) -> int:
        return len(self.volumes)

    # -- disk watchdog (disk_location.go:314-345) ----------------------------
    def check_disk_space(self) -> bool:
        """Flips all volumes read-only when free space is low; returns the
        current is-low state."""
        usage = shutil.disk_usage(self.directory)
        low = usage.free / usage.total < self.min_free_space_ratio
        if low:
            with self._lock:
                for v in self.volumes.values():
                    v.read_only = True
        return low

    def close(self) -> None:
        with self._lock:
            for v in self.volumes.values():
                v.close()
            for ev in self.ec_volumes.values():
                ev.close()
            self.volumes.clear()
            self.ec_volumes.clear()
