"""Needle: the on-disk record of one stored file.

Byte-compatible with the reference's v1/v2/v3 layouts
(`weed/storage/needle/needle.go:24`, `needle_read_write.go:33-128`):

    header (16B):  cookie u32BE | id u64BE | size u32BE
    v2/v3 body (size bytes, only if data present):
        data_size u32BE | data | flags u8
        [name_size u8 | name]           if FLAG_HAS_NAME
        [mime_size u8 | mime]           if FLAG_HAS_MIME
        [last_modified 5B BE]           if FLAG_HAS_LAST_MODIFIED
        [ttl 2B]                        if FLAG_HAS_TTL
        [pairs_size u16BE | pairs]      if FLAG_HAS_PAIRS
    checksum u32BE (masked CRC-32C of data, crc.go:24)
    v3 only: append_at_ns u64BE
    padding to the next 8-byte boundary — ALWAYS 1..8 bytes
      (PaddingLength returns 8, not 0, when already aligned —
       needle_read_write.go:298-304)

Padding-byte contents replicate a quirk of the reference: the writer reuses
its header scratch buffer, so v1/v2 padding bytes are a prefix of the
big-endian needle id, and v3 padding bytes are the big-endian size followed
by zeros (needle_read_write.go:114-122 — the appended slice
``header[NeedleChecksumSize(+TimestampSize):...+padding]`` aliases those
previously-written fields). We reproduce this so .dat files are bit-identical.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import crc as crc32c
from .ttl import TTL, EMPTY_TTL, load_ttl_from_bytes
from .types import (
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_PADDING_SIZE,
    TIMESTAMP_SIZE,
    bytes_to_cookie,
    bytes_to_needle_id,
    bytes_to_size,
    cookie_to_bytes,
    needle_id_to_bytes,
    size_to_bytes,
)

# flags (needle_read_write.go:15-25)
FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


class SizeMismatchError(Exception):
    pass


class CrcError(Exception):
    pass


def padding_length(needle_size: int, version: int) -> int:
    """Bytes of padding after the record — always in 1..8 (never 0)."""
    if version == VERSION3:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    return NEEDLE_PADDING_SIZE - (used % NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    extra = TIMESTAMP_SIZE if version == VERSION3 else 0
    return needle_size + NEEDLE_CHECKSUM_SIZE + extra + padding_length(needle_size, version)


def get_actual_size(needle_size: int, version: int) -> int:
    return NEEDLE_HEADER_SIZE + needle_body_length(needle_size, version)


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0  # sum of data_size,data,name_size,name,mime_size,mime,...
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0  # unix seconds; only low 5 bytes stored
    ttl: TTL = field(default_factory=lambda: EMPTY_TTL)
    checksum: int = 0  # raw (unmasked) CRC-32C of data
    append_at_ns: int = 0  # v3

    # -- flag helpers --------------------------------------------------------
    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set_flag(self, flag: int) -> None:
        # sweedlint: ok cross-domain-race per-request Needle; one request path builds it, never shared across domains
        self.flags |= flag

    @property
    def is_compressed(self) -> bool:
        return self.has(FLAG_IS_COMPRESSED)

    @property
    def is_chunk_manifest(self) -> bool:
        return self.has(FLAG_IS_CHUNK_MANIFEST)

    def etag(self) -> str:
        return struct.pack(">I", self.checksum & 0xFFFFFFFF).hex()

    # -- size computation (needle_read_write.go:62-81) -----------------------
    def _computed_size(self) -> int:
        if len(self.data) == 0:
            return 0
        size = 4 + len(self.data) + 1
        if self.has(FLAG_HAS_NAME):
            size += 1 + min(len(self.name), 0xFF)
        if self.has(FLAG_HAS_MIME):
            size += 1 + len(self.mime)
        if self.has(FLAG_HAS_LAST_MODIFIED):
            size += LAST_MODIFIED_BYTES_LENGTH
        if self.has(FLAG_HAS_TTL):
            size += TTL_BYTES_LENGTH
        if self.has(FLAG_HAS_PAIRS):
            size += 2 + len(self.pairs)
        return size

    # -- serialization -------------------------------------------------------
    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        """The full on-disk record (prepareWriteBuffer, needle_read_write.go:33)."""
        self.checksum = crc32c.new(self.data)
        if version == VERSION1:
            # sweedlint: ok cross-domain-race per-request Needle; one request path builds it, never shared across domains
            self.size = len(self.data)
            out = bytearray()
            out += cookie_to_bytes(self.cookie)
            out += needle_id_to_bytes(self.id)
            out += size_to_bytes(self.size)
            out += self.data
            out += struct.pack(">I", crc32c.masked_value(self.checksum))
            pad = padding_length(self.size, version)
            # quirk: v1 padding aliases the header's id bytes
            out += needle_id_to_bytes(self.id)[:pad]
            return bytes(out)
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        self.size = self._computed_size()
        out = bytearray()
        out += cookie_to_bytes(self.cookie)
        out += needle_id_to_bytes(self.id)
        out += size_to_bytes(self.size)
        if len(self.data) > 0:
            out += struct.pack(">I", len(self.data))
            out += self.data
            out += bytes([self.flags & 0xFF])
            if self.has(FLAG_HAS_NAME):
                name = self.name[:0xFF]
                out += bytes([len(name)])
                out += name
            if self.has(FLAG_HAS_MIME):
                out += bytes([len(self.mime) & 0xFF])
                out += self.mime
            if self.has(FLAG_HAS_LAST_MODIFIED):
                out += struct.pack(">Q", self.last_modified)[8 - LAST_MODIFIED_BYTES_LENGTH :]
            if self.has(FLAG_HAS_TTL):
                out += self.ttl.to_bytes()
            if self.has(FLAG_HAS_PAIRS):
                out += struct.pack(">H", len(self.pairs))
                out += self.pairs
        pad = padding_length(self.size, version)
        out += struct.pack(">I", crc32c.masked_value(self.checksum))
        if version == VERSION2:
            # quirk: v2 padding aliases the header's id bytes
            out += needle_id_to_bytes(self.id)[:pad]
        else:
            out += struct.pack(">Q", self.append_at_ns)
            # quirk: v3 padding aliases the header's size bytes, then zeros
            pad_src = size_to_bytes(self.size) + b"\x00" * 4
            out += pad_src[:pad]
        return bytes(out)

    # -- deserialization -----------------------------------------------------
    def parse_header(self, b: bytes) -> None:
        self.cookie = bytes_to_cookie(b[0:4])  # sweedlint: ok cross-domain-race per-request Needle; one request path builds it, never shared across domains
        self.id = bytes_to_needle_id(b[4:12])
        self.size = bytes_to_size(b[12:16])

    def _read_body_v2(self, b: bytes) -> None:
        """Parse the v2/v3 body fields (readNeedleDataVersion2, :219-278)."""
        idx = 0
        n = len(b)
        if idx < n:
            data_size = struct.unpack(">I", b[idx : idx + 4])[0]
            idx += 4
            if data_size + idx >= n:
                # the flags byte always follows the data — a data_size that
                # leaves no room for it is a corrupt length prefix
                raise ValueError("needle body truncated: data")
            self.data = bytes(b[idx : idx + data_size])
            idx += data_size
            self.flags = b[idx]
            idx += 1
        if idx < n and self.has(FLAG_HAS_NAME):
            name_size = b[idx]
            idx += 1
            if name_size + idx > n:
                raise ValueError("needle body truncated: name")
            self.name = bytes(b[idx : idx + name_size])
            idx += name_size
        if idx < n and self.has(FLAG_HAS_MIME):
            mime_size = b[idx]
            idx += 1
            if mime_size + idx > n:
                raise ValueError("needle body truncated: mime")
            self.mime = bytes(b[idx : idx + mime_size])
            idx += mime_size
        if idx < n and self.has(FLAG_HAS_LAST_MODIFIED):
            if LAST_MODIFIED_BYTES_LENGTH + idx > n:
                raise ValueError("needle body truncated: last_modified")
            self.last_modified = int.from_bytes(
                b[idx : idx + LAST_MODIFIED_BYTES_LENGTH], "big"
            )
            idx += LAST_MODIFIED_BYTES_LENGTH
        if idx < n and self.has(FLAG_HAS_TTL):
            if TTL_BYTES_LENGTH + idx > n:
                raise ValueError("needle body truncated: ttl")
            self.ttl = load_ttl_from_bytes(b[idx : idx + TTL_BYTES_LENGTH])
            idx += TTL_BYTES_LENGTH
        if idx < n and self.has(FLAG_HAS_PAIRS):
            if 2 + idx > n:
                raise ValueError("needle body truncated: pairs size")
            pairs_size = struct.unpack(">H", b[idx : idx + 2])[0]
            idx += 2
            if pairs_size + idx > n:
                raise ValueError("needle body truncated: pairs")
            # sweedlint: ok cross-domain-race per-request Needle; one request path builds it, never shared across domains
            self.pairs = bytes(b[idx : idx + pairs_size])
            idx += pairs_size

    @classmethod
    def from_bytes(
        cls, b: bytes, size: int, version: int = CURRENT_VERSION, verify_crc: bool = True
    ) -> "Needle":
        """Hydrate from a full record blob (ReadBytes, needle_read_write.go:170)."""
        n = cls()
        n.parse_header(b)
        if n.size != size:
            raise SizeMismatchError(f"found size {n.size}, expected {size}")
        if version == VERSION1:
            n.data = bytes(b[NEEDLE_HEADER_SIZE : NEEDLE_HEADER_SIZE + size])
        else:
            n._read_body_v2(b[NEEDLE_HEADER_SIZE : NEEDLE_HEADER_SIZE + size])
        if size > 0 and verify_crc:
            stored = struct.unpack(
                ">I",
                b[NEEDLE_HEADER_SIZE + size : NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE],
            )[0]
            actual = crc32c.new(n.data)
            if stored != crc32c.masked_value(actual):
                raise CrcError("CRC error! data on disk corrupted")
            n.checksum = actual
        if version == VERSION3:
            ts_off = NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE
            n.append_at_ns = struct.unpack(">Q", b[ts_off : ts_off + TIMESTAMP_SIZE])[0]
        return n

    def read_body_bytes(self, body: bytes, version: int) -> None:
        """Parse a body read separately from the header (ReadNeedleBodyBytes, :330)."""
        if not body:
            return
        if version == VERSION1:
            self.data = bytes(body[: self.size])
        else:
            self._read_body_v2(body[: self.size])
            if version == VERSION3:
                ts_off = self.size + NEEDLE_CHECKSUM_SIZE
                self.append_at_ns = struct.unpack(
                    ">Q", body[ts_off : ts_off + TIMESTAMP_SIZE]
                )[0]
        self.checksum = crc32c.new(self.data)


def parse_needle_header(b: bytes) -> tuple[int, int, int]:
    """(cookie, id, size) from a 16-byte header."""
    return bytes_to_cookie(b[0:4]), bytes_to_needle_id(b[4:12]), bytes_to_size(b[12:16])
