"""Volume superblock: the first 8 bytes of every .dat file.

Layout (`weed/storage/super_block/super_block.go:16-23`):
    byte 0:    needle version (1/2/3)
    byte 1:    replica placement byte (xyz)
    bytes 2-3: TTL (count, unit)
    bytes 4-5: compaction revision u16BE
    bytes 6-7: extra-size u16BE (0 unless pb extra present), extra follows
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .needle import CURRENT_VERSION
from .replica_placement import ReplicaPlacement
from .ttl import TTL, EMPTY_TTL, load_ttl_from_bytes

SUPER_BLOCK_SIZE = 8


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=lambda: EMPTY_TTL)
    compaction_revision: int = 0
    extra: bytes = b""  # serialized SuperBlockExtra pb, rarely used

    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + (len(self.extra) if self.extra else 0)

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        struct.pack_into(">H", header, 4, self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError("super block extra too large")
            struct.pack_into(">H", header, 6, len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("super block truncated")
        version = b[0]
        if version not in (1, 2, 3):
            raise ValueError(f"unsupported volume version {version}")
        rp = ReplicaPlacement.from_byte(b[1])
        ttl = load_ttl_from_bytes(b[2:4])
        rev = struct.unpack(">H", b[4:6])[0]
        extra_size = struct.unpack(">H", b[6:8])[0]
        extra = bytes(b[8 : 8 + extra_size]) if extra_size else b""
        return cls(version, rp, ttl, rev, extra)
