"""CRC-32C (Castagnoli) needle checksums, with the reference's masked value.

The reference checksums needle data with CRC-32C (`weed/storage/needle/crc.go`,
klauspost/crc32 Castagnoli table) and stores a *masked* value on disk:

    Value() = rotr32(crc, 15) + 0xa282ead8        (crc.go:24-26)

(the snappy/leveldb CRC mask). Both the raw crc and the masked value are
exposed here. A C++ kernel (slicing-by-8) is used when the native library is
available; otherwise a Python table implementation is used.
"""

from __future__ import annotations

CASTAGNOLI_POLY_REFLECTED = 0x82F63B78
_MASK_DELTA = 0xA282EAD8

# 8 tables for slicing-by-8 (table[0] is the classic byte-at-a-time table).
_TABLES: list[list[int]] = []


def _build_tables() -> None:
    t0 = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (CASTAGNOLI_POLY_REFLECTED if crc & 1 else 0)
        t0.append(crc)
    _TABLES.append(t0)
    for k in range(1, 8):
        prev = _TABLES[k - 1]
        _TABLES.append([(prev[i] >> 8) ^ t0[prev[i] & 0xFF] for i in range(256)])


_build_tables()

_native_update = None


def _try_load_native() -> None:
    global _native_update
    try:
        from seaweedfs_tpu.native import lib as _nl

        _native_update = _nl.crc32c_update
    except Exception:
        _native_update = None


_try_load_native()


def _py_update(crc: int, data: bytes) -> int:
    crc ^= 0xFFFFFFFF
    t = _TABLES
    n = len(data)
    i = 0
    # slicing-by-8
    mv = memoryview(data)
    while n - i >= 8:
        crc ^= int.from_bytes(mv[i : i + 4], "little")
        crc = (
            t[7][crc & 0xFF]
            ^ t[6][(crc >> 8) & 0xFF]
            ^ t[5][(crc >> 16) & 0xFF]
            ^ t[4][(crc >> 24) & 0xFF]
            ^ t[3][mv[i + 4]]
            ^ t[2][mv[i + 5]]
            ^ t[1][mv[i + 6]]
            ^ t[0][mv[i + 7]]
        )
        i += 8
    while i < n:
        crc = (crc >> 8) ^ t[0][(crc ^ mv[i]) & 0xFF]
        i += 1
    return crc ^ 0xFFFFFFFF


def update(crc: int, data: bytes) -> int:
    """Incremental CRC-32C, matching Go's ``crc32.Update`` semantics."""
    if _native_update is not None:
        return _native_update(crc, data)
    return _py_update(crc, data)


def new(data: bytes = b"") -> int:
    """CRC-32C of ``data`` from a zero seed (crc.go:16-18)."""
    return update(0, data)


def masked_value(crc: int) -> int:
    """The value actually stored on disk (crc.go:24-26)."""
    rot = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rot + _MASK_DELTA) & 0xFFFFFFFF
