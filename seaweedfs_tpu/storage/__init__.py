"""Storage engine: needle format, volumes, needle maps, superblock.

Byte-compatible with the reference's `weed/storage` layer.
"""
