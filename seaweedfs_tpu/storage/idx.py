"""Index (.idx / .ecx) entries: 16 bytes each (with 4-byte offsets).

Layout per entry (`weed/storage/idx/walk.go:49-55`):
    key u64BE | offset (4 or 5 bytes, scaled /8) | size u32BE (signed)

``walk_index_file`` streams entries in file order (append order for .idx,
ascending-key order for .ecx).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable, Iterator

from .types import (
    NEEDLE_ID_SIZE,
    OFFSET_SIZE,
    SIZE_SIZE,
    bytes_to_needle_id,
    bytes_to_offset,
    bytes_to_size,
    needle_id_to_bytes,
    needle_map_entry_size,
    offset_to_bytes,
    size_to_bytes,
)

ROWS_TO_READ = 1024


def pack_entry(key: int, actual_offset: int, size: int, offset_size: int = OFFSET_SIZE) -> bytes:
    """One index entry; ``actual_offset`` is the real byte offset (stored /8)."""
    return (
        needle_id_to_bytes(key)
        + offset_to_bytes(actual_offset, offset_size)
        + size_to_bytes(size)
    )


def unpack_entry(b: bytes, offset_size: int = OFFSET_SIZE) -> tuple[int, int, int]:
    """(key, actual_offset, size) from one entry."""
    key = bytes_to_needle_id(b[:NEEDLE_ID_SIZE])
    off = bytes_to_offset(b[NEEDLE_ID_SIZE : NEEDLE_ID_SIZE + offset_size], offset_size)
    size = bytes_to_size(
        b[NEEDLE_ID_SIZE + offset_size : NEEDLE_ID_SIZE + offset_size + SIZE_SIZE]
    )
    return key, off, size


def iter_index_file(
    r: BinaryIO, offset_size: int = OFFSET_SIZE
) -> Iterator[tuple[int, int, int]]:
    """Yield (key, actual_offset, size) for every entry in an index stream."""
    entry_size = needle_map_entry_size(offset_size)
    r.seek(0)
    while True:
        chunk = r.read(entry_size * ROWS_TO_READ)
        if not chunk:
            return
        for i in range(0, len(chunk) - entry_size + 1, entry_size):
            yield unpack_entry(chunk[i : i + entry_size], offset_size)
        if len(chunk) % entry_size:
            return  # torn tail entry — ignore, matching reference tolerance


def walk_index_file(
    r: BinaryIO,
    fn: Callable[[int, int, int], None],
    offset_size: int = OFFSET_SIZE,
) -> None:
    for key, off, size in iter_index_file(r, offset_size):
        fn(key, off, size)


def iter_index_bytes(
    b: bytes, offset_size: int = OFFSET_SIZE
) -> Iterator[tuple[int, int, int]]:
    yield from iter_index_file(io.BytesIO(b), offset_size)
