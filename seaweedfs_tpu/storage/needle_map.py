"""Needle maps: per-volume NeedleId → (offset, size) index.

Mirrors `weed/storage/needle_map.go` + `needle_map_memory.go`: every mutation
is also appended to the .idx file (the map's durable log / checkpoint);
deletes append a (key, tombstone_offset, -1) entry. Counters match the
reference's mapMetric (`needle_map_metric.go`): FileCount counts every put
ever applied (including overwrites), DeletionCounter counts both explicit
deletes and overwrite-shadowed needles.

The reference's CompactMap packs entries into 16 bytes each; a Python dict
costs ~100 bytes/entry, so CompactNeedleMap here keeps the hot map in a plain
dict for speed but the design isolates it behind NeedleMapper so a
numpy-packed variant can swap in for RAM-constrained deployments.
"""

from __future__ import annotations

import io
import os
import threading
from dataclasses import dataclass
from typing import BinaryIO, Callable, Iterator, Optional

from . import idx as idx_mod
from .types import OFFSET_SIZE, TOMBSTONE_FILE_SIZE, size_is_valid


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # actual byte offset
    size: int


class NeedleMapper:
    """Interface (needle_map.go:21-34)."""

    def put(self, key: int, offset: int, size: int) -> None:
        raise NotImplementedError

    def get(self, key: int) -> Optional[NeedleValue]:
        raise NotImplementedError

    def delete(self, key: int, offset: int) -> None:
        raise NotImplementedError

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        raise NotImplementedError

    def release(self) -> None:
        """Drop auxiliary resources (db handles, caches) WITHOUT closing the
        shared .idx file — called before the owner swaps in a fresh map over
        the same index handle. No-op for purely in-memory kinds."""

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        pass


class IdxLogMixin:
    """Shared .idx append log + mapMetric boilerplate for all map kinds.

    Subclass __init__ must set `_index_file`, `_offset_size`, and the five
    counters (file_counter, file_byte_counter, deletion_counter,
    deletion_byte_counter, max_file_key)."""

    def _init_log(self, index_file: BinaryIO, offset_size: int) -> None:
        self._index_file = index_file
        self._offset_size = offset_size
        self.file_counter = 0
        self.file_byte_counter = 0
        self.deletion_counter = 0
        self.deletion_byte_counter = 0
        self.max_file_key = 0

    def _append_entry(self, key: int, offset: int, size: int) -> None:
        entry = idx_mod.pack_entry(key, offset, size, self._offset_size)
        self._index_file.seek(0, io.SEEK_END)
        self._index_file.write(entry)

    def content_size(self) -> int:
        return self.file_byte_counter

    def deleted_size(self) -> int:
        return self.deletion_byte_counter

    def file_count(self) -> int:
        return self.file_counter

    def deleted_count(self) -> int:
        return self.deletion_counter

    def index_file_size(self) -> int:
        try:
            return os.fstat(self._index_file.fileno()).st_size
        except (OSError, AttributeError, io.UnsupportedOperation):
            self._index_file.seek(0, io.SEEK_END)
            return self._index_file.tell()

    def sync(self) -> None:
        self._index_file.flush()
        try:
            os.fsync(self._index_file.fileno())
        except (OSError, AttributeError, io.UnsupportedOperation):
            pass

    def close(self) -> None:
        try:
            self._index_file.flush()
        except ValueError:
            pass
        self._index_file.close()


class CompactNeedleMap(IdxLogMixin, NeedleMapper):
    """In-memory map + .idx append log (NeedleMapInMemory kind)."""

    def __init__(self, index_file: BinaryIO, offset_size: int = OFFSET_SIZE):
        self._m: dict[int, tuple[int, int]] = {}
        self._lock = threading.Lock()
        self._init_log(index_file, offset_size)

    # -- loading (needle_map_memory.go:30-51) --------------------------------
    @classmethod
    def load(cls, index_file: BinaryIO, offset_size: int = OFFSET_SIZE) -> "CompactNeedleMap":
        nm = cls(index_file, offset_size)
        for key, offset, size in idx_mod.iter_index_file(index_file, offset_size):
            nm.max_file_key = max(nm.max_file_key, key)
            if offset != 0 and size_is_valid(size):
                nm.file_counter += 1
                nm.file_byte_counter += size
                old = nm._m.get(key)
                nm._m[key] = (offset, size)
                if old is not None and old[0] != 0 and size_is_valid(old[1]):
                    nm.deletion_counter += 1
                    nm.deletion_byte_counter += old[1]
            else:
                old = nm._m.get(key)
                nm.deletion_counter += 1
                if old is not None and size_is_valid(old[1]):
                    nm.deletion_byte_counter += old[1]
                    # mark deleted in place, preserving the original offset
                    # (compact_map.go Delete negates Size so read-deleted
                    # can still find the old record); absent keys are a
                    # no-op like the reference's m.Delete
                    nm._m[key] = (old[0], -old[1])
        index_file.seek(0, io.SEEK_END)
        return nm

    # -- mutations -----------------------------------------------------------
    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            old = self._m.get(key)
            self._m[key] = (offset, size)
            self.max_file_key = max(self.max_file_key, key)
            self.file_counter += 1
            self.file_byte_counter += size
            if old is not None and old[0] != 0 and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
            self._append_entry(key, offset, size)

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._m.get(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> None:
        """offset = where the tombstone needle was appended in the .dat.

        The in-memory entry keeps the ORIGINAL offset with a negated size
        (compact_map.go Delete) so deleted records remain addressable for
        read-deleted flows; only the .idx log records the tombstone offset.
        """
        with self._lock:
            old = self._m.get(key)
            if old is not None and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
                self._m[key] = (old[0], -old[1])
            self._append_entry(key, offset, TOMBSTONE_FILE_SIZE)

    # -- queries -------------------------------------------------------------
    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._m):
            offset, size = self._m[key]
            fn(NeedleValue(key, offset, size))

    def items(self) -> Iterator[NeedleValue]:
        for key, (offset, size) in self._m.items():
            yield NeedleValue(key, offset, size)

    def __len__(self) -> int:
        return len(self._m)
