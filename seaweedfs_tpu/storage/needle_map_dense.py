"""Memory-dense needle map kinds: 16 bytes/entry, plus on-disk spill.

The Haystack point of the whole system is that a volume's needle index fits
in RAM at 16 bytes per entry (`weed/storage/needle_map/compact_map.go:173`
— sectioned sorted arrays + an overflow map; BASELINE.md "per-file RAM
index entry: 16 bytes"). A Python dict costs ~100 bytes/entry, which is the
wrong memory profile at millions of needles per volume.

Kinds here (needle_map.go:12-19 analog):

- ``DenseNeedleMap`` — NeedleMapInMemory with the reference's memory
  profile: parallel numpy arrays (key u64 + scaled-offset u32 + size i32 =
  16B exactly; the 5-byte-offset flavor adds a u8 high-byte plane, matching
  the reference's `OffsetHigher` extra byte). Sorted base + small overflow
  dict for recent inserts, merged in batches — the same sorted-base +
  overflow shape as `compact_map.go`, with numpy `searchsorted` instead of
  hand-rolled binary search. Loading a .idx is fully vectorized (no
  per-entry Python objects), so a million-needle volume indexes in tens of
  milliseconds and ~16MB.
- ``SqliteNeedleMap`` — the LevelDB kind (`needle_map_leveldb.go:26`):
  entries live in an on-disk B-tree beside the volume for indexes too big
  for RAM; metric counters persist in a meta table so a clean load is O(1),
  and a crash (meta out of date vs the .idx) triggers a vectorized replay.
- ``SortedFileNeedleMap`` — the read-only kind
  (`needle_map_sorted_file.go:19`): binary-searches a key-sorted index file
  (.sdx) directly on disk, zero resident entries; for sealed volumes.
- ``MmapNeedleMap`` — the billion-needle kind: the same key-sorted base
  format memory-mapped read-only (np.memmap over `<volume>.mdx`), so
  lookups fault in O(log n) pages and a 1e8–1e9-entry index stays
  page-cache-resident with near-zero RSS; mutations shadow the base in an
  overflow dict and batched merges atomically rewrite the mapped file.
"""

from __future__ import annotations

import io
import json
import mmap
import os
import threading
from typing import BinaryIO, Callable, Iterator, Optional

import numpy as np

from . import idx as idx_mod
from .needle_map import IdxLogMixin, NeedleMapper, NeedleValue
from .types import (
    NEEDLE_PADDING_SIZE,
    OFFSET_SIZE,
    TOMBSTONE_FILE_SIZE,
    needle_map_entry_size,
    size_is_valid,
)

# sqlite binds signed 64-bit ints only; needle keys are full u64, so keys
# are stored bias-shifted by 2^63 — the shift is order-preserving, so
# ORDER BY stays ascending-key
_KEY_BIAS = 1 << 63


def _parse_entry_matrix(
    a: np.ndarray, offset_size: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(n, entry)-shaped uint8 rows → (keys u64, scaled offsets u64,
    sizes i64); only the sliced columns are ever copied, so the input can
    be a memmap without faulting the whole file in."""
    keys = a[:, :8].copy().view(">u8").ravel().astype(np.uint64)
    if offset_size == 4:
        offs = a[:, 8:12].copy().view(">u4").ravel().astype(np.uint64)
    else:
        # 5-byte flavor: 4 low bytes big-endian + most-significant 5th byte
        # (types.py offset encoding)
        lo = a[:, 8:12].copy().view(">u4").ravel().astype(np.uint64)
        hi = a[:, 12].astype(np.uint64)
        offs = (hi << np.uint64(32)) | lo
    sizes = (
        a[:, 8 + offset_size : 8 + offset_size + 4]
        .copy()
        .view(">i4")
        .ravel()
        .astype(np.int64)
    )
    return keys, offs, sizes


def _parse_idx_arrays(
    raw: bytes, offset_size: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized .idx parse → (keys u64, scaled offsets u64, sizes i64)."""
    entry = needle_map_entry_size(offset_size)
    n = len(raw) // entry
    a = np.frombuffer(raw[: n * entry], dtype=np.uint8).reshape(n, entry)
    return _parse_entry_matrix(a, offset_size)


def _pack_entries(
    keys: np.ndarray,
    scaled_offs: np.ndarray,
    sizes: np.ndarray,
    offset_size: int,
) -> np.ndarray:
    """Inverse of _parse_entry_matrix: (n, entry) uint8 rows byte-identical
    to a pack_entry loop, without per-entry Python."""
    n = len(keys)
    entry = needle_map_entry_size(offset_size)
    a = np.empty((n, entry), dtype=np.uint8)
    a[:, :8] = (
        np.ascontiguousarray(keys, dtype=np.uint64)
        .astype(">u8")
        .view(np.uint8)
        .reshape(n, 8)
    )
    so = np.ascontiguousarray(scaled_offs, dtype=np.uint64)
    a[:, 8:12] = (
        (so & np.uint64(0xFFFFFFFF)).astype(">u4").view(np.uint8).reshape(n, 4)
    )
    if offset_size == 5:
        a[:, 12] = (so >> np.uint64(32)).astype(np.uint8)
    a[:, 8 + offset_size : 8 + offset_size + 4] = (
        np.ascontiguousarray(sizes, dtype=np.int64)
        .astype(">i4")
        .view(np.uint8)
        .reshape(n, 4)
    )
    return a


def replay_idx_vectorized(raw: bytes, offset_size: int):
    """Replay a whole .idx history without per-entry Python.

    Returns (metrics, final_keys u64 sorted, final_scaled_offs u64,
    final_sizes i64) where metrics is a dict of the mapMetric counters with
    CompactNeedleMap-identical semantics (needle_map_metric.go): every put
    counts toward file_counter, overwrites and deletes of a live put count
    toward the deletion counters, and a key whose last action is a
    tombstone keeps its final put's offset with a negated size.
    """
    keys, offs, sizes = _parse_idx_arrays(raw, offset_size)
    n = len(keys)
    empty = np.empty(0, dtype=np.uint64)
    metrics = dict(file_counter=0, file_byte_counter=0, deletion_counter=0,
                   deletion_byte_counter=0, max_file_key=0)
    if n == 0:
        return metrics, empty, empty, np.empty(0, dtype=np.int64)
    puts = (offs != 0) & (sizes > 0)
    metrics["max_file_key"] = int(keys.max())
    metrics["file_counter"] = int(puts.sum())
    metrics["file_byte_counter"] = int(sizes[puts].sum())
    # per-key sequences: stable sort groups each key's entries in append
    # order, so "previous state was a live put" is a shift within the run
    order = np.argsort(keys, kind="stable")
    k_s, p_s, sz_s, off_s = keys[order], puts[order], sizes[order], offs[order]
    same_prev = np.empty(n, dtype=bool)
    same_prev[0] = False
    same_prev[1:] = k_s[1:] == k_s[:-1]
    prev_valid = np.empty(n, dtype=bool)
    prev_valid[0] = False
    prev_valid[1:] = p_s[:-1]
    prev_valid &= same_prev
    # a delete always counts; a put over a live put shadows it
    metrics["deletion_counter"] = int((~p_s).sum() + (p_s & prev_valid).sum())
    prev_size = np.empty(n, dtype=np.int64)
    prev_size[0] = 0
    prev_size[1:] = sz_s[:-1]
    metrics["deletion_byte_counter"] = int(prev_size[prev_valid].sum())
    # final state per key: last put wins; a later tombstone negates it
    starts = np.nonzero(~same_prev)[0]
    ends = np.concatenate([starts[1:], np.array([n])]) - 1
    put_idx = np.where(p_s, np.arange(n), -1)
    last_put = np.maximum.reduceat(put_idx, starts)
    has_put = last_put >= 0
    lp = last_put[has_put]
    fsizes = sz_s[lp]
    fsizes = np.where(ends[has_put] > lp, -fsizes, fsizes)
    return metrics, k_s[starts[has_put]].copy(), off_s[lp].copy(), fsizes


class DenseNeedleMap(IdxLogMixin, NeedleMapper):
    """16B/entry packed in-memory kind (compact_map.go analog)."""

    MERGE_THRESHOLD = 8192
    # overflow is also allowed to grow to base/MERGE_RATIO before merging:
    # a fixed trigger makes every sustained PUT storm pay an O(base) re-sort
    # per 8192 inserts (quadratic overall); ratio-scaled batches keep the
    # total merge work O(n log n) — each merge grows the base by ≥1/8, so
    # per-insert cost is amortized O(1) array work
    MERGE_RATIO = 8

    def __init__(self, index_file: BinaryIO, offset_size: int = OFFSET_SIZE):
        self._lock = threading.Lock()
        self._init_log(index_file, offset_size)
        self._keys = np.empty(0, dtype=np.uint64)  # sorted, unique
        self._offs = np.empty(0, dtype=np.uint32)  # scaled (/8)
        self._offs_hi = (
            np.empty(0, dtype=np.uint8) if offset_size == 5 else None
        )
        self._sizes = np.empty(0, dtype=np.int32)
        # overflow holds only keys NOT in the base (updates to base keys go
        # in place), so lookups check it first and merge is a pure union
        self._overflow: dict[int, tuple[int, int]] = {}
        self.merge_count = 0  # diagnostic: merges since load

    def _merge_trigger(self) -> int:
        """Overflow size that forces a merge: MERGE_THRESHOLD is the floor
        (small bases keep the old behavior), scaled up with the base so
        merge cost stays amortized under sustained insert storms."""
        return max(self.MERGE_THRESHOLD, len(self._keys) // self.MERGE_RATIO)

    # -- loading (vectorized; no per-entry Python) ---------------------------
    @classmethod
    def load(
        cls, index_file: BinaryIO, offset_size: int = OFFSET_SIZE
    ) -> "DenseNeedleMap":
        nm = cls(index_file, offset_size)
        index_file.seek(0)
        raw = index_file.read()
        index_file.seek(0, io.SEEK_END)
        metrics, fkeys, foffs, fsizes = replay_idx_vectorized(raw, offset_size)
        nm.__dict__.update(metrics)
        nm._keys = fkeys
        nm._offs = foffs.astype(np.uint32)
        if nm._offs_hi is not None:
            nm._offs_hi = (foffs >> np.uint64(32)).astype(np.uint8)
        nm._sizes = fsizes.astype(np.int32)
        return nm

    # -- internals -----------------------------------------------------------
    def _base_find(self, key: int) -> Optional[int]:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return i
        return None

    def _base_value(self, i: int) -> tuple[int, int]:
        scaled = int(self._offs[i])
        if self._offs_hi is not None:
            scaled |= int(self._offs_hi[i]) << 32
        return scaled * NEEDLE_PADDING_SIZE, int(self._sizes[i])

    def _base_set(self, i: int, offset: int, size: int) -> None:
        scaled = offset // NEEDLE_PADDING_SIZE
        self._offs[i] = scaled & 0xFFFFFFFF
        if self._offs_hi is not None:
            self._offs_hi[i] = scaled >> 32
        self._sizes[i] = size

    def _current(self, key: int) -> Optional[tuple[int, int]]:
        v = self._overflow.get(key)
        if v is not None:
            return v
        i = self._base_find(key)
        return self._base_value(i) if i is not None else None

    def _merge_overflow(self) -> None:
        if not self._overflow:
            return
        ok = np.fromiter(self._overflow.keys(), dtype=np.uint64,
                         count=len(self._overflow))
        vals = list(self._overflow.values())
        ooff = np.array([v[0] // NEEDLE_PADDING_SIZE for v in vals],
                        dtype=np.uint64)
        osz = np.array([v[1] for v in vals], dtype=np.int32)
        order = np.argsort(ok)
        ok, ooff, osz = ok[order], ooff[order], osz[order]
        pos = np.searchsorted(self._keys, ok)
        self._keys = np.insert(self._keys, pos, ok)
        self._offs = np.insert(self._offs, pos,
                               (ooff & 0xFFFFFFFF).astype(np.uint32))
        if self._offs_hi is not None:
            self._offs_hi = np.insert(
                self._offs_hi, pos, (ooff >> np.uint64(32)).astype(np.uint8)
            )
        self._sizes = np.insert(self._sizes, pos, osz)
        self._overflow.clear()
        self.merge_count += 1

    # -- mutations (CompactNeedleMap-identical semantics) --------------------
    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            old = self._current(key)
            if key in self._overflow:
                self._overflow[key] = (offset, size)
            else:
                i = self._base_find(key)
                if i is not None:
                    self._base_set(i, offset, size)
                else:
                    self._overflow[key] = (offset, size)
                    if len(self._overflow) >= self._merge_trigger():
                        self._merge_overflow()
            self.max_file_key = max(self.max_file_key, key)
            self.file_counter += 1
            self.file_byte_counter += size
            if old is not None and old[0] != 0 and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
            self._append_entry(key, offset, size)

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._current(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> None:
        with self._lock:
            old = self._current(key)
            if old is not None and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
                if key in self._overflow:
                    self._overflow[key] = (old[0], -old[1])
                else:
                    i = self._base_find(key)
                    if i is not None:
                        self._sizes[i] = -old[1]
            self._append_entry(key, offset, TOMBSTONE_FILE_SIZE)

    # -- queries -------------------------------------------------------------
    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for nv in self._ascending_items():
            fn(nv)

    def _ascending_items(self) -> Iterator[NeedleValue]:
        ov = sorted(self._overflow.items())
        oi = 0
        for bi in range(len(self._keys)):
            key = int(self._keys[bi])
            while oi < len(ov) and ov[oi][0] < key:
                k, (o, s) = ov[oi]
                yield NeedleValue(k, o, s)
                oi += 1
            off, size = self._base_value(bi)
            yield NeedleValue(key, off, size)
        while oi < len(ov):
            k, (o, s) = ov[oi]
            yield NeedleValue(k, o, s)
            oi += 1

    def items(self) -> Iterator[NeedleValue]:
        return self._ascending_items()

    def __len__(self) -> int:
        return len(self._keys) + len(self._overflow)

    def bytes_per_entry(self) -> float:
        """Resident index bytes per entry (diagnostic; the design target is
        16, matching compact_map.go — overflow entries cost dict rates
        until merged)."""
        n = len(self)
        if n == 0:
            return 0.0
        base = (
            self._keys.nbytes
            + self._offs.nbytes
            + self._sizes.nbytes
            + (self._offs_hi.nbytes if self._offs_hi is not None else 0)
        )
        return (base + len(self._overflow) * 100) / n


class SqliteNeedleMap(IdxLogMixin, NeedleMapper):
    """On-disk spill kind for RAM-exceeding volumes (needle_map_leveldb.go).

    Entries live in a SQLite B-tree next to the volume (`<base>.ldb`). The
    .idx append log stays the durable source of truth (EC encode, copy,
    and rebuild all read .idx): db commits are deferred to sync()/close(),
    and a load whose committed meta doesn't match the .idx size (crash,
    torn tail, compaction) drops the db and replays the .idx vectorized.
    """

    def __init__(
        self,
        index_file: BinaryIO,
        db_path: str,
        offset_size: int = OFFSET_SIZE,
    ):
        import sqlite3

        self._lock = threading.Lock()
        self._init_log(index_file, offset_size)
        self._db_path = db_path
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS needles"
            " (key INTEGER PRIMARY KEY, offset INTEGER, size INTEGER)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v INTEGER)"
        )

    _META_KEYS = (
        "file_counter",
        "file_byte_counter",
        "deletion_counter",
        "deletion_byte_counter",
        "max_file_key",
    )

    @classmethod
    def load(
        cls,
        index_file: BinaryIO,
        db_path: str,
        offset_size: int = OFFSET_SIZE,
    ) -> "SqliteNeedleMap":
        nm = cls(index_file, db_path, offset_size)
        meta = {k: int(v) for k, v in nm._db.execute("SELECT k, v FROM meta")}
        idx_size = nm.index_file_size()
        if meta.get("idx_size", -1) == idx_size:
            for k in cls._META_KEYS:
                setattr(nm, k, int(meta.get(k, 0)))
        else:
            nm._rebuild_from_idx()
        index_file.seek(0, io.SEEK_END)
        return nm

    def _rebuild_from_idx(self) -> None:
        """Vectorized replay of the .idx (db missing or out of date, e.g.
        after a crash between an idx append and the next commit)."""
        self._db.execute("DELETE FROM needles")
        self._index_file.seek(0)
        raw = self._index_file.read()
        metrics, fkeys, foffs, fsizes = replay_idx_vectorized(
            raw, self._offset_size
        )
        self.__dict__.update(metrics)
        actual = (foffs * np.uint64(NEEDLE_PADDING_SIZE)).astype(np.int64)
        # vectorized bias shift: (key XOR 2^63) reinterpreted as i64 equals
        # key - 2^63 for all u64 keys (order-preserving)
        skeys = (fkeys ^ np.uint64(_KEY_BIAS)).view(np.int64)
        self._db.executemany(
            "INSERT INTO needles VALUES (?,?,?)",
            zip(skeys.tolist(), actual.tolist(), fsizes.tolist()),
        )
        self._commit_meta()
        self._db.commit()

    def _commit_meta(self) -> None:
        # values stored as text: max_file_key is a full u64 and would
        # overflow sqlite's signed-integer binding
        self._db.executemany(
            "INSERT OR REPLACE INTO meta VALUES (?,?)",
            [(k, str(getattr(self, k))) for k in self._META_KEYS]
            + [("idx_size", str(self.index_file_size()))],
        )

    @staticmethod
    def _sk(key: int) -> int:
        """u64 needle key → signed 64-bit sqlite key (order-preserving)."""
        return key - _KEY_BIAS

    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            sk = self._sk(key)
            row = self._db.execute(
                "SELECT offset, size FROM needles WHERE key=?", (sk,)
            ).fetchone()
            self._db.execute(
                "INSERT OR REPLACE INTO needles VALUES (?,?,?)",
                (sk, offset, size),
            )
            self.max_file_key = max(self.max_file_key, key)
            self.file_counter += 1
            self.file_byte_counter += size
            if row is not None and row[0] != 0 and size_is_valid(row[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += row[1]
            self._append_entry(key, offset, size)

    def get(self, key: int) -> Optional[NeedleValue]:
        row = self._db.execute(
            "SELECT offset, size FROM needles WHERE key=?", (self._sk(key),)
        ).fetchone()
        if row is None:
            return None
        return NeedleValue(key, row[0], row[1])

    def delete(self, key: int, offset: int) -> None:
        with self._lock:
            sk = self._sk(key)
            row = self._db.execute(
                "SELECT offset, size FROM needles WHERE key=?", (sk,)
            ).fetchone()
            if row is not None and size_is_valid(row[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += row[1]
                self._db.execute(
                    "UPDATE needles SET size=? WHERE key=?", (-row[1], sk)
                )
            self._append_entry(key, offset, TOMBSTONE_FILE_SIZE)

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for nv in self.items():
            fn(nv)

    def items(self) -> Iterator[NeedleValue]:
        for skey, offset, size in self._db.execute(
            "SELECT key, offset, size FROM needles ORDER BY key"
        ):
            yield NeedleValue(skey + _KEY_BIAS, offset, size)

    def __len__(self) -> int:
        return self._db.execute("SELECT COUNT(*) FROM needles").fetchone()[0]

    def sync(self) -> None:
        super().sync()
        with self._lock:
            self._commit_meta()
            self._db.commit()

    def release(self) -> None:
        self._db.close()

    def close(self) -> None:
        super().close()
        try:
            with self._lock:
                self._commit_meta()
                self._db.commit()
            self._db.close()
        except Exception:  # sweedlint: ok broad-except shutdown close; the mmap flush above already made state durable
            pass

    def destroy(self) -> None:
        self.close()
        try:
            os.remove(self._db_path)  # sweedlint: ok durability destroy path; deletion is the goal and re-running is idempotent
        except FileNotFoundError:
            pass


def _write_sorted_entries(
    keys: np.ndarray,
    scaled_offs: np.ndarray,
    sizes: np.ndarray,
    sorted_path: str,
    offset_size: int,
) -> None:
    """Write key-sorted final-state entries atomically (tmp + rename)."""
    a = _pack_entries(keys, scaled_offs, sizes, offset_size)
    with open(sorted_path + ".tmp", "wb") as f:
        f.write(a.tobytes())
    # sweedlint: ok durability atomic tmp+rename of derived data; the sorted base rebuilds from .idx
    os.replace(sorted_path + ".tmp", sorted_path)


def write_sorted_index(
    idx_raw: bytes, sorted_path: str, offset_size: int = OFFSET_SIZE
) -> None:
    """Replay an .idx history and write the final state key-sorted (.sdx),
    the input format of the read-only kind (WriteSortedFileFromIdx,
    ec_encoder.go:27 is the .ecx sibling of this)."""
    _, fkeys, foffs, fsizes = replay_idx_vectorized(idx_raw, offset_size)
    _write_sorted_entries(fkeys, foffs, fsizes, sorted_path, offset_size)


class SortedFileNeedleMap(IdxLogMixin, NeedleMapper):
    """Read-only kind: binary search a key-sorted index file on disk
    (needle_map_sorted_file.go:19). Zero resident entries; used for sealed
    read-only volumes where even 16B/entry is too much."""

    def __init__(
        self,
        sorted_path: str,
        offset_size: int = OFFSET_SIZE,
        index_file: Optional[BinaryIO] = None,
    ):
        self._f = open(sorted_path, "rb")
        self._entry = needle_map_entry_size(offset_size)
        self._count = os.fstat(self._f.fileno()).st_size // self._entry
        self._lock = threading.Lock()
        self._init_log(index_file or self._f, offset_size)
        # counters from one streaming pass (transient, nothing resident)
        raw = self._f.read()
        keys, offs, sizes = _parse_idx_arrays(raw, offset_size)
        if len(keys):
            self.max_file_key = int(keys.max())
            live = sizes > 0
            self.file_counter = int(live.sum())
            self.file_byte_counter = int(sizes[live].sum())
            self.deletion_counter = int((~live).sum())
            self.deletion_byte_counter = int(-sizes[~live].sum())

    def _read(self, i: int) -> tuple[int, int, int]:
        with self._lock:
            self._f.seek(i * self._entry)
            return idx_mod.unpack_entry(
                self._f.read(self._entry), self._offset_size
            )

    def get(self, key: int) -> Optional[NeedleValue]:
        lo, hi = 0, self._count
        while lo < hi:
            mid = (lo + hi) // 2
            k, off, size = self._read(mid)
            if k == key:
                return NeedleValue(k, off, size)
            if k < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    def put(self, key: int, offset: int, size: int) -> None:
        raise io.UnsupportedOperation("sorted-file needle map is read-only")

    def delete(self, key: int, offset: int) -> None:
        raise io.UnsupportedOperation("sorted-file needle map is read-only")

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for i in range(self._count):
            k, off, size = self._read(i)
            fn(NeedleValue(k, off, size))

    def __len__(self) -> int:
        return self._count

    def close(self) -> None:
        self._f.close()
        if self._index_file is not self._f:
            super().close()


class MmapNeedleMap(IdxLogMixin, NeedleMapper):
    """Memory-mapped kind for volumes whose index exceeds RAM even at
    16 B/entry (the 1e8–1e9-needle hot-shard profile).

    The base (`<volume>.mdx`) is the final .idx replay state, key-sorted in
    the exact pack_entry byte format of .sdx, mapped read-only with
    np.memmap: a get() binary-searches the mapping and faults in only the
    O(log n) pages it touches, so resident memory is page-cache pressure,
    not heap. Mutations SHADOW the immutable base through an overflow dict
    (CompactNeedleMap conventions: negative size marks a delete in place)
    and are batch-merged by atomically rewriting the mapped file with the
    same ratio-amortized trigger as DenseNeedleMap.

    A JSON sidecar (`<volume>.mdx.meta`) pins the .idx size + counters the
    base reflects, so a fresh load maps the base without reading the .idx
    at all (near-zero RSS at any entry count). A stale or missing sidecar —
    crash between idx appends and the next merge, torn-tail truncation,
    compaction — rebuilds from the .idx via the vectorized replay (O(idx
    bytes) transient, nothing resident afterwards). The .idx append log
    stays the durable source of truth; base + sidecar are derived data.
    """

    MERGE_THRESHOLD = 8192
    MERGE_RATIO = 8

    _META_KEYS = SqliteNeedleMap._META_KEYS

    def __init__(
        self,
        index_file: BinaryIO,
        base_path: str,
        offset_size: int = OFFSET_SIZE,
    ):
        self._lock = threading.Lock()
        self._init_log(index_file, offset_size)
        self._base_path = base_path
        self._meta_path = base_path + ".meta"
        self._entry = needle_map_entry_size(offset_size)
        self._mm: Optional[np.memmap] = None
        self._count = 0
        # overflow shadows the base (the mapping is immutable): updates AND
        # deletes of base keys live here until the next merge
        self._overflow: dict[int, tuple[int, int]] = {}
        self.merge_count = 0

    # -- loading -------------------------------------------------------------
    @classmethod
    def load(
        cls,
        index_file: BinaryIO,
        base_path: str,
        offset_size: int = OFFSET_SIZE,
    ) -> "MmapNeedleMap":
        nm = cls(index_file, base_path, offset_size)
        meta = nm._read_meta()
        if (
            meta is not None
            and meta.get("idx_size") == nm.index_file_size()
            and meta.get("offset_size") == offset_size
            and os.path.exists(base_path)
            and os.path.getsize(base_path)
            == meta.get("count", -1) * nm._entry
        ):
            for k in cls._META_KEYS:
                setattr(nm, k, int(meta.get(k, 0)))
            nm._map_base(int(meta["count"]))
        else:
            nm._rebuild_from_idx()
        index_file.seek(0, io.SEEK_END)
        return nm

    def _read_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self) -> None:
        meta = {k: getattr(self, k) for k in self._META_KEYS}
        meta["idx_size"] = self.index_file_size()
        meta["offset_size"] = self._offset_size
        meta["count"] = self._count
        with open(self._meta_path + ".tmp", "w", encoding="utf-8") as f:
            json.dump(meta, f)
        # sweedlint: ok durability derived sidecar; a torn meta just forces an .idx replay on the next load
        os.replace(self._meta_path + ".tmp", self._meta_path)

    def _map_base(self, count: int) -> None:
        self._count = count
        if count > 0:
            self._mm = np.memmap(  # sweedlint: ok lock-discipline helper; callers hold the lock (put/delete/close) or run in load before the map is shared
                self._base_path,
                dtype=np.uint8,
                mode="r",
                shape=(count * self._entry,),
            )
            # binary search is pure random access: without MADV_RANDOM the
            # kernel's readahead/fault-around maps whole 64KB clusters per
            # touched page, ballooning RSS toward the full base size when
            # the file is warm in page cache
            raw = getattr(self._mm, "_mmap", None)  # sweedlint: ok lock-discipline helper; callers hold the lock or run in load before the map is shared
            if raw is not None and hasattr(mmap, "MADV_RANDOM"):
                raw.madvise(mmap.MADV_RANDOM)
        else:
            # np.memmap refuses zero-length files
            self._mm = None  # sweedlint: ok lock-discipline helper; callers hold the lock or run in load before the map is shared

    def _rebuild_from_idx(self) -> None:
        self._index_file.seek(0)
        raw = self._index_file.read()
        metrics, fkeys, foffs, fsizes = replay_idx_vectorized(
            raw, self._offset_size
        )
        self.__dict__.update(metrics)
        self._write_base(fkeys, foffs, fsizes)

    def _write_base(
        self, keys: np.ndarray, scaled_offs: np.ndarray, sizes: np.ndarray
    ) -> None:
        # drop our mapping before the rename; a map another thread already
        # holds stays valid (the replaced inode lives until unmapped)
        self._mm = None  # sweedlint: ok lock-discipline helper; callers (put/delete/close/load) serialize through the lock
        _write_sorted_entries(
            keys, scaled_offs, sizes, self._base_path, self._offset_size
        )
        self._map_base(len(keys))
        self._write_meta()

    # -- base lookups --------------------------------------------------------
    def _key_at(self, i: int) -> int:
        s = i * self._entry
        return int.from_bytes(self._mm[s : s + 8].tobytes(), "big")  # sweedlint: ok lock-discipline read helper under the caller's lock (get/put/delete)

    def _entry_at(self, i: int) -> tuple[int, int, int]:
        s = i * self._entry
        return idx_mod.unpack_entry(
            self._mm[s : s + self._entry].tobytes(), self._offset_size  # sweedlint: ok lock-discipline read helper under the caller's lock (get/put/delete)
        )

    def _base_find(self, key: int) -> Optional[int]:
        lo, hi = 0, self._count
        while lo < hi:
            mid = (lo + hi) // 2
            k = self._key_at(mid)
            if k == key:
                return mid
            if k < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    def _current(self, key: int) -> Optional[tuple[int, int]]:
        v = self._overflow.get(key)
        if v is not None:
            return v
        i = self._base_find(key)
        if i is None:
            return None
        _, off, size = self._entry_at(i)
        return off, size

    # -- mutations (CompactNeedleMap-identical semantics) --------------------
    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            old = self._current(key)
            self._overflow[key] = (offset, size)
            self.max_file_key = max(self.max_file_key, key)
            self.file_counter += 1
            self.file_byte_counter += size
            if old is not None and old[0] != 0 and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
            self._append_entry(key, offset, size)
            if len(self._overflow) >= max(
                self.MERGE_THRESHOLD, self._count // self.MERGE_RATIO
            ):
                self._merge_overflow()

    def get(self, key: int) -> Optional[NeedleValue]:
        with self._lock:
            v = self._current(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> None:
        with self._lock:
            old = self._current(key)
            if old is not None and size_is_valid(old[1]):
                self.deletion_counter += 1
                self.deletion_byte_counter += old[1]
                self._overflow[key] = (old[0], -old[1])
            self._append_entry(key, offset, TOMBSTONE_FILE_SIZE)

    def _merge_overflow(self) -> None:
        if not self._overflow:
            return
        if self._count:
            a = np.asarray(self._mm).reshape(self._count, self._entry)  # sweedlint: ok lock-discipline merge runs under the put/close caller's lock
            bkeys, boffs, bsizes = _parse_entry_matrix(a, self._offset_size)
        else:
            bkeys = np.empty(0, dtype=np.uint64)
            boffs = np.empty(0, dtype=np.uint64)
            bsizes = np.empty(0, dtype=np.int64)
        ok = np.fromiter(
            self._overflow.keys(), dtype=np.uint64, count=len(self._overflow)
        )
        vals = list(self._overflow.values())
        ooff = np.array(
            [v[0] // NEEDLE_PADDING_SIZE for v in vals], dtype=np.uint64
        )
        osz = np.array([v[1] for v in vals], dtype=np.int64)
        order = np.argsort(ok)
        ok, ooff, osz = ok[order], ooff[order], osz[order]
        pos = np.searchsorted(bkeys, ok)
        hit = pos < len(bkeys)
        hit[hit] = bkeys[pos[hit]] == ok[hit]
        boffs[pos[hit]] = ooff[hit]
        bsizes[pos[hit]] = osz[hit]
        ins = ~hit
        bkeys = np.insert(bkeys, pos[ins], ok[ins])
        boffs = np.insert(boffs, pos[ins], ooff[ins])
        bsizes = np.insert(bsizes, pos[ins], osz[ins])
        self._overflow.clear()
        self._write_base(bkeys, boffs, bsizes)
        self.merge_count += 1

    # -- queries -------------------------------------------------------------
    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for nv in self._ascending_items():
            fn(nv)

    def _ascending_items(self) -> Iterator[NeedleValue]:
        ov = sorted(self._overflow.items())
        oi = 0
        for bi in range(self._count):
            key, off, size = self._entry_at(bi)
            while oi < len(ov) and ov[oi][0] < key:
                k, (o, s) = ov[oi]
                yield NeedleValue(k, o, s)
                oi += 1
            if oi < len(ov) and ov[oi][0] == key:
                k, (o, s) = ov[oi]  # overflow shadows the base entry
                yield NeedleValue(k, o, s)
                oi += 1
            else:
                yield NeedleValue(key, off, size)
        while oi < len(ov):
            k, (o, s) = ov[oi]
            yield NeedleValue(k, o, s)
            oi += 1

    def items(self) -> Iterator[NeedleValue]:
        return self._ascending_items()

    def __len__(self) -> int:
        shadowed = sum(
            1 for k in self._overflow if self._base_find(k) is not None
        )
        return self._count + len(self._overflow) - shadowed

    # -- lifecycle -----------------------------------------------------------
    def release(self) -> None:
        with self._lock:
            self._mm = None
            self._overflow.clear()

    def close(self) -> None:
        try:
            with self._lock:
                self._merge_overflow()
                self._write_meta()
                self._mm = None
        except Exception:  # sweedlint: ok broad-except shutdown close; base+meta are derived, the next load replays the .idx
            pass
        super().close()

    def destroy(self) -> None:
        self.close()
        for p in (self._base_path, self._meta_path):
            try:
                # sweedlint: ok durability destroy path; deletion is the goal and re-running is idempotent
                os.remove(p)
            except FileNotFoundError:
                pass
