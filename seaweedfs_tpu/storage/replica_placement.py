"""Replica placement: the xyz digit scheme (e.g. "001", "200").

Matches `weed/storage/super_block/replica_placement.go`: x = copies in other
data centers, y = copies on other racks (same DC), z = copies on other servers
(same rack). Stored as one byte: x*100 + y*10 + z.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicaPlacement:
    diff_data_center_count: int = 0
    diff_rack_count: int = 0
    same_rack_count: int = 0

    @classmethod
    def from_string(cls, t: str) -> "ReplicaPlacement":
        vals = [0, 0, 0]
        for i, c in enumerate(t):
            count = ord(c) - ord("0")
            if not 0 <= count <= 2:
                raise ValueError(f"unknown replication type {t!r}")
            if i < 3:
                vals[i] = count
        return cls(vals[0], vals[1], vals[2])

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.from_string(f"{b:03d}")

    def to_byte(self) -> int:
        return (
            self.diff_data_center_count * 100
            + self.diff_rack_count * 10
            + self.same_rack_count
        )

    def copy_count(self) -> int:
        return (
            self.diff_data_center_count
            + self.diff_rack_count
            + self.same_rack_count
            + 1
        )

    def __str__(self) -> str:
        return (
            f"{self.diff_data_center_count}"
            f"{self.diff_rack_count}"
            f"{self.same_rack_count}"
        )
