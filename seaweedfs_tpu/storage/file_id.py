"""File IDs: ``<volumeId>,<needleKeyHex><cookieHex8>`` e.g. ``3,01637037d6``.

Matches `weed/storage/needle/file_id.go` and `needle.go:120-165`
(ParsePath / ParseNeedleIdCookie / formatNeedleIdCookie): the hex blob is the
8-byte big-endian needle id with leading zero *bytes* stripped, followed by
the 4-byte cookie (always 8 hex chars).
"""

from __future__ import annotations

from dataclasses import dataclass

from .types import (
    COOKIE_SIZE,
    NEEDLE_ID_SIZE,
    cookie_to_bytes,
    needle_id_to_bytes,
    parse_cookie,
    parse_needle_id,
)


def format_needle_id_cookie(key: int, cookie: int) -> str:
    b = needle_id_to_bytes(key) + cookie_to_bytes(cookie)
    nz = 0
    while nz < NEEDLE_ID_SIZE and b[nz] == 0:
        nz += 1
    return b[nz:].hex()


def parse_needle_id_cookie(key_hash: str) -> tuple[int, int]:
    if len(key_hash) <= COOKIE_SIZE * 2:
        raise ValueError(f"key hash {key_hash!r} too short")
    if len(key_hash) > (NEEDLE_ID_SIZE + COOKIE_SIZE) * 2:
        raise ValueError(f"key hash {key_hash!r} too long")
    split = len(key_hash) - COOKIE_SIZE * 2
    return parse_needle_id(key_hash[:split]), parse_cookie(key_hash[split:])


def parse_path(fid: str) -> tuple[int, int]:
    """fid path segment → (needle id, cookie); supports the ``_<delta>`` suffix
    used by chunked uploads (needle.go:120-142)."""
    if len(fid) <= COOKIE_SIZE * 2:
        raise ValueError(f"invalid fid {fid!r}")
    delta = 0
    if "_" in fid:
        fid, delta_str = fid.rsplit("_", 1)
        delta = int(delta_str)
    nid, cookie = parse_needle_id_cookie(fid)
    return nid + delta, cookie


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{format_needle_id_cookie(self.key, self.cookie)}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        comma = fid.find(",")
        if comma <= 0:
            raise ValueError(f"wrong fid format {fid!r}")
        vid = int(fid[:comma])
        # accept the ``_<delta>`` batch-assign suffix like parse_path does
        # (needle.go ParsePath): assign(count=n) hands out base, base_1 …
        # base_{n-1} and those fids flow through entry chunk lists into
        # lookup/delete grouping, which parses them here
        key, cookie = parse_path(fid[comma + 1 :])
        return cls(vid, key, cookie)
