"""Store: all disk locations of one volume server; routes needle ops.

Mirrors `weed/storage/store.go` + `store_ec.go`: volume CRUD across
DiskLocations, heartbeat stat collection with delta queues for the master
stream, and the EC read path with on-the-fly reconstruction:

    local shard read → remote shard fetch (injected callback; the volume
    server wires this to gRPC in the cluster layer) → reconstruction from
    ≥k sibling shards via the EC codec (TPU/CPU) — store_ec.go:122-375.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..ec.codec import Codec, get_codec
from ..ec.constants import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, TOTAL_SHARDS, shard_ext
from ..ec.ec_volume import EcVolume, NeedsShardError
from ..ec.ec_volume import NotFoundError as EcNotFoundError
from ..stats import heat
from ..util import faultpoints, glog
from .commit import StagedCommit
from .disk_location import DiskLocation
from .needle import Needle
from .replica_placement import ReplicaPlacement
from .ttl import EMPTY_TTL, TTL, read_ttl
from .volume import NotFoundError, Volume
from ..util.locks import make_rlock

# remote_reader(vid, shard_id, offset, size) -> bytes | None
RemoteShardReader = Callable[[int, int, int, int], Optional[bytes]]


class Store:
    def __init__(
        self,
        directories: list[str],
        ip: str = "localhost",
        port: int = 8080,
        public_url: str = "",
        ec_backend: Optional[str] = None,
        needle_map_kind: str = "dense",
        remote_fetch_attempts: int = 3,
        remote_fetch_backoff_s: float = 0.05,
        remote_fetch_timeout_s: float = 5.0,
    ):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.needle_map_kind = needle_map_kind
        # degraded-read remote fetch policy: bounded attempts, exponential
        # backoff, and a per-range deadline so a wedged peer degrades to
        # reconstruction instead of hanging the read
        self.remote_fetch_attempts = remote_fetch_attempts
        self.remote_fetch_backoff_s = remote_fetch_backoff_s
        self.remote_fetch_timeout_s = remote_fetch_timeout_s
        self.locations = [
            DiskLocation(d, needle_map_kind=needle_map_kind)
            for d in directories
        ]
        for loc in self.locations:
            loc.load_existing_volumes()
        self._ec_codec: Optional[Codec] = None
        self._ec_backend = ec_backend
        self.remote_shard_reader: Optional[RemoteShardReader] = None
        # native turbo data plane (native/turbo.py); set by the volume
        # server when it owns the public port through the engine
        self.turbo_engine = None
        # delta queues consumed by the heartbeat loop (store.go:33-50 —
        # NewVolumesChan etc.); entries are heartbeat message dicts so the
        # master can apply them without a full sync. delta_event wakes the
        # heartbeat loop for an instant delta beat, the analog of the
        # reference's select over the Store channels
        # (volume_grpc_client_to_master.go:155-197).
        self.new_volumes: deque[dict] = deque()
        self.deleted_volumes: deque[dict] = deque()
        self.new_ec_shards: deque[dict] = deque()
        self.deleted_ec_shards: deque[dict] = deque()
        self.delta_event = threading.Event()
        # EC volumes have no Volume.read_heat — their read heat lives here,
        # marked on the EC needle-read path and shipped in the EC heartbeat
        # so the lifecycle controller can spot hot EC volumes to un-EC
        self.ec_read_heat: dict[int, heat.EwmaHeat] = {}
        # scrub findings (SWEED_SCRUB): corrupt needle/shard ids per vid,
        # carried in heartbeats so the master-resident lifecycle controller
        # can schedule a rebuild / replica re-fetch; cleared when the local
        # copy is deleted, re-copied, or rebuilt
        self.corrupt_needles: dict[int, set[int]] = {}
        self.corrupt_shards: dict[int, set[int]] = {}
        self._lock = make_rlock("Store._lock")
        heat.register_store(self)

    @property
    def ec_codec(self) -> Codec:
        if self._ec_codec is None:
            self._ec_codec = get_codec(self._ec_backend)
        return self._ec_codec

    # -- volume management (store.go:120-200) --------------------------------
    def add_volume(
        self,
        vid: int,
        collection: str = "",
        replica_placement: str | ReplicaPlacement = "000",
        ttl: str | TTL = "",
        preallocate: int = 0,
    ) -> Volume:
        if self.find_volume(vid) is not None:
            raise ValueError(f"volume {vid} already exists")
        if isinstance(replica_placement, str):
            replica_placement = ReplicaPlacement.from_string(replica_placement)
        if isinstance(ttl, str):
            ttl = read_ttl(ttl) if ttl else EMPTY_TTL
        loc = self._pick_location()
        v = Volume(loc.directory, collection, vid, replica_placement, ttl,
                   needle_map_kind=self.needle_map_kind)
        loc.add_volume(v)
        self.attach_turbo_volume(v)
        self.queue_new_volume(v)
        return v

    def attach_turbo_volume(self, v: Volume) -> None:
        """Hand a volume's data plane to the native engine (if one is up).
        Replicated volumes keep HTTP writes in Python (fan-out logic) but
        still delegate index/append ownership for reads."""
        if self.turbo_engine is None:
            return
        writable_http = v.super_block.replica_placement.copy_count() == 1
        v.attach_turbo(self.turbo_engine, writable_http)

    def attach_turbo_all(self) -> None:
        for loc in self.locations:
            for v in list(loc.volumes.values()):
                self.attach_turbo_volume(v)

    def _pick_location(self) -> DiskLocation:
        return min(self.locations, key=lambda l: l.volume_count())

    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.find_volume(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.find_ec_volume(vid)
            if ev is not None:
                return ev
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def delete_volume(self, vid: int) -> bool:
        v = self.find_volume(vid)
        msg = self._volume_message(v) if v is not None else {"id": vid}
        for loc in self.locations:
            if loc.delete_volume(vid):
                with self._lock:
                    self.deleted_volumes.append(msg)
                self.delta_event.set()
                return True
        return False

    def unmount_volume(self, vid: int) -> bool:
        """Stop serving a volume but keep its files on disk, announcing the
        removal like delete_volume does (VolumeUnmount)."""
        v = self.find_volume(vid)
        if v is None:
            return False
        msg = self._volume_message(v)
        for loc in self.locations:
            if loc.unload_volume(vid):
                with self._lock:
                    self.deleted_volumes.append(msg)
                self.delta_event.set()
                return True
        return False

    def mount_volume(self, vid: int) -> Optional[Volume]:
        """(Re)load exactly one volume from disk — not every unmounted
        volume sharing the directory — and announce it."""
        from .disk_location import parse_volume_base_name

        if self.find_volume(vid) is not None:
            return self.find_volume(vid)
        for loc in self.locations:
            for name in os.listdir(loc.directory):
                if not name.endswith(".dat"):
                    continue
                try:
                    collection, v_id = parse_volume_base_name(name[:-4])
                except ValueError:
                    continue
                if v_id != vid:
                    continue
                v = Volume(
                    loc.directory, collection, vid,
                    create_if_missing=False,
                    needle_map_kind=loc.needle_map_kind,
                )
                loc.add_volume(v)
                self.attach_turbo_volume(v)
                self.queue_new_volume(v)
                return v
        return None

    # -- delta beat plumbing -------------------------------------------------
    def queue_new_volume(self, v: Volume) -> None:
        with self._lock:
            self.new_volumes.append(self._volume_message(v))
        self.delta_event.set()

    def queue_new_ec_shards(self, vid: int, collection: str, bits: int) -> None:
        with self._lock:
            self.new_ec_shards.append(
                {"id": vid, "collection": collection, "ec_index_bits": bits}
            )
        self.delta_event.set()

    def queue_deleted_ec_shards(
        self, vid: int, collection: str, bits: int
    ) -> None:
        with self._lock:
            self.deleted_ec_shards.append(
                {"id": vid, "collection": collection, "ec_index_bits": bits}
            )
        self.delta_event.set()

    def drain_deltas(self) -> dict:
        """Pop all queued delta messages; empty dict when nothing pending."""
        with self._lock:
            out = {}
            for key, q in (
                ("new_volumes", self.new_volumes),
                ("deleted_volumes", self.deleted_volumes),
                ("new_ec_shards", self.new_ec_shards),
                ("deleted_ec_shards", self.deleted_ec_shards),
            ):
                if q:
                    out[key] = list(q)
                    q.clear()
            self.delta_event.clear()
            return out

    def mark_volume_readonly(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = True
        return True

    def mark_volume_writable(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = False
        return True

    # -- needle ops (store.go:299-340) ---------------------------------------
    def write_volume_needle(
        self, vid: int, n: Needle, fsync: bool = False
    ) -> tuple[int, int, bool]:
        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        v.write_heat.mark()
        return v.write_needle(n, fsync=fsync)

    def delete_volume_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            ev = self.find_ec_volume(vid)
            if ev is not None:
                ev.delete_needle(n.id)
                return 0
            raise NotFoundError(f"volume {vid} not found")
        v.write_heat.mark()
        return v.delete_needle(n)

    def read_volume_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is not None:
            v.read_heat.mark()
            return v.read_needle(n)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return self.read_ec_shard_needle(ev, n)
        raise NotFoundError(f"volume {vid} not found")

    def read_volume_needle_extent(self, vid: int, n: Needle, min_size: int = 0):
        """Zero-copy read setup for plain volumes (Volume.read_needle_extent);
        EC-striped data has no contiguous on-disk extent → None (callers
        fall back to the buffered read)."""
        v = self.find_volume(vid)
        if v is None:
            return None
        v.read_heat.mark()
        return v.read_needle_extent(n, min_size)

    def note_volume_read(self, vid: int) -> None:
        """Account a read that was answered without touching the volume
        (hot-needle cache hit): the heat signal must still see it or the
        cache would mask exactly the skew placement needs to react to."""
        v = self.find_volume(vid)
        if v is not None:
            v.read_heat.mark()

    # -- EC encode: crash-safe two-phase commit ------------------------------
    def ec_encode_volume(self, vid: int) -> list[int]:
        """Stripe a sealed volume into 14 shards + .ecx + .vif with an
        all-or-nothing commit (VolumeEcShardsGenerate, hardened).

        Every output is written to a ``.tmp`` staging name; files are
        fsync'd, a commit manifest is written atomically, and only then do
        the staged files take their final names (storage/commit.py). A
        crash anywhere leaves the volume either fully plain-readable (the
        .dat is untouched; staged files are GC'd at restart) or fully
        EC-readable (the manifest rolls the rename pass forward). Returns
        the shard ids generated.
        """
        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        v.read_only = True
        v.sync()
        base = v.file_name()
        from ..ec import encoder

        sc = StagedCommit(base, "ec.encode")
        for sid in range(TOTAL_SHARDS):
            sc.stage(base + shard_ext(sid))
        sc.stage(base + ".ecx")
        vif_tmp = sc.stage(base + ".vif")
        try:
            encoder.write_ec_files(base, self.ec_codec, suffix=".tmp")
            encoder.write_sorted_file_from_idx(base, ext=".ecx.tmp")
            # per-shard sha256 into the .vif: the scrub thread's integrity
            # ground truth (RS is deterministic — rebuilds hash identically)
            import hashlib

            sums = []
            for sid in range(TOTAL_SHARDS):
                digest = hashlib.sha256()
                with open(base + shard_ext(sid) + ".tmp", "rb") as sf:
                    for chunk in iter(lambda: sf.read(1 << 20), b""):
                        digest.update(chunk)
                sums.append(digest.hexdigest())
            encoder.save_volume_info(
                vif_tmp,
                version=v.version,
                replication=str(v.super_block.replica_placement),
                shard_sums=sums,
            )
            sc.commit()
        except BaseException:
            sc.abort()
            raise
        return list(range(TOTAL_SHARDS))

    # -- scrub findings (consumed by cluster/lifecycle.py via heartbeats) ----
    def report_corrupt_needle(self, vid: int, nid: int) -> None:
        with self._lock:
            found = self.corrupt_needles.setdefault(vid, set())
            if nid in found:
                return  # already flagged: don't re-trigger delta beats
            found.add(nid)
        self.delta_event.set()  # instant beat: repair shouldn't wait a pulse

    def report_corrupt_shard(self, vid: int, sid: int) -> None:
        with self._lock:
            found = self.corrupt_shards.setdefault(vid, set())
            if sid in found:
                return
            found.add(sid)
        self.delta_event.set()

    def clear_corrupt(self, vid: int, shard_ids=None) -> None:
        """Forget scrub findings for a vid — the local copy was deleted,
        re-fetched, or rebuilt; the next scrub round re-validates."""
        with self._lock:
            self.corrupt_needles.pop(vid, None)
            if shard_ids is None:
                self.corrupt_shards.pop(vid, None)
            else:
                left = self.corrupt_shards.get(vid)
                if left is not None:
                    left -= set(shard_ids)
                    if not left:
                        self.corrupt_shards.pop(vid, None)

    # -- EC read path (store_ec.go:122-375) ----------------------------------
    def read_ec_shard_needle(self, ev: EcVolume, n: Needle) -> int:
        h = self.ec_read_heat.get(ev.id)
        if h is None:
            h = self.ec_read_heat.setdefault(ev.id, heat.EwmaHeat())
        h.mark()
        offset, size, intervals = ev.locate_needle(n.id)
        blob = b"".join(self._read_interval(ev, iv) for iv in intervals)
        m = Needle.from_bytes(blob, size, ev.version)
        if m.id != n.id:
            raise EcNotFoundError(f"unexpected needle {m.id:x} != {n.id:x}")
        n.__dict__.update(m.__dict__)
        return len(n.data)

    def _read_interval(self, ev: EcVolume, interval) -> bytes:
        try:
            return ev.read_interval_local(interval)
        except NeedsShardError:
            sid, soff = interval.to_shard_id_and_offset(
                LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, ev.data_shards
            )
            # 1. remote shard holder (wired to gRPC by the volume server)
            data = self._remote_shard_read(ev.id, sid, soff, interval.size)
            if data is not None:
                return data
            # 2. degraded mode: reconstruct from sibling shards
            return self._recover_interval(ev, sid, soff, interval.size)

    def _remote_shard_read(
        self, vid: int, sid: int, offset: int, size: int
    ) -> Optional[bytes]:
        """Remote shard fetch with bounded retry/backoff/deadline
        (store_ec.go readRemoteEcShardInterval, hardened). A flaky peer
        gets ``remote_fetch_attempts`` tries with exponential backoff; a
        dead or wedged one costs at most ``remote_fetch_timeout_s`` before
        the caller falls through to reconstruction. Returns None when the
        range is unobtainable remotely."""
        if self.remote_shard_reader is None:
            return None
        from ..util.retry import TRANSIENT, RetryError, RetryPolicy, retry_call

        def _fetch():
            faultpoints.fire("ec.read.remote-fetch")
            data = self.remote_shard_reader(vid, sid, offset, size)
            if data is None or len(data) != size:
                # a short range is a failed attempt, not a success
                raise IOError(f"short/empty remote range for {vid}.{sid}")
            return data

        policy = RetryPolicy(
            attempts=max(1, self.remote_fetch_attempts),
            base_s=self.remote_fetch_backoff_s,
            cap_s=max(1.0, self.remote_fetch_backoff_s * 8),
            deadline_s=self.remote_fetch_timeout_s,
        )
        try:
            return retry_call(
                _fetch,
                policy=policy,
                # every failure mode here (peer down, timeout, short read,
                # injected fault) heals the same way: try again, then fall
                # through to reconstruction — nothing is poison
                classify=lambda e: TRANSIENT,
                on_retry=lambda e, attempt, delay: glog.warning(
                    "remote shard %d.%d fetch attempt %d failed: %s",
                    vid, sid, attempt, e,
                ),
            )
        except RetryError:
            return None

    def _recover_interval(
        self, ev: EcVolume, missing_shard: int, offset: int, size: int
    ) -> bytes:
        """Fetch the same byte range from ≥k sibling shards and RS-decode
        (recoverOneRemoteEcShardInterval, store_ec.go:322)."""
        codec = self.ec_codec
        shards: list[Optional[np.ndarray]] = [None] * ev.total_shards
        have = 0
        for sid in range(ev.total_shards):
            if sid == missing_shard:
                continue
            local = ev.shards.get(sid)
            buf = None
            if local is not None:
                buf = local.read_at(offset, size)
            else:
                buf = self._remote_shard_read(ev.id, sid, offset, size)
            if buf is not None and len(buf) == size:
                shards[sid] = np.frombuffer(buf, dtype=np.uint8)
                have += 1
            if have >= ev.data_shards:
                break
        if have < ev.data_shards:
            raise EcNotFoundError(
                f"volume {ev.id} shard {missing_shard}: only {have} shards reachable"
            )
        rebuilt = codec.reconstruct(shards, data_only=missing_shard < ev.data_shards)
        return rebuilt[missing_shard].tobytes()

    # -- heartbeat (store.go:204-297) ----------------------------------------
    def _volume_message(self, v: Volume) -> dict:
        return {
            "id": v.id,
            "size": v.size(),
            "collection": v.collection,
            "file_count": v.file_count(),
            "delete_count": v.deleted_count(),
            "deleted_byte_count": v.deleted_size(),
            "read_only": v.read_only,
            "replica_placement": v.super_block.replica_placement.to_byte(),
            "version": v.version,
            "ttl": v.ttl.to_uint32(),
            "compact_revision": v.super_block.compaction_revision,
            "read_heat": round(v.read_heat.value(), 3),
            "write_heat": round(v.write_heat.value(), 3),
            # lifecycle inputs: where the bytes live + what scrub flagged
            "remote_tier": v.is_tiered(),
            "corrupt_needles": len(self.corrupt_needles.get(v.id, ())),
        }

    def collect_heartbeat(self) -> dict:
        volumes = []
        max_file_key = 0
        for loc in self.locations:
            for v in loc.volumes.values():
                max_file_key = max(max_file_key, v.max_file_key())
                volumes.append(self._volume_message(v))
        return {
            "ip": self.ip,
            "port": self.port,
            "public_url": self.public_url,
            "max_file_key": max_file_key,
            "max_volume_count": sum(l.max_volume_count for l in self.locations),
            "volumes": volumes,
        }

    def collect_ec_heartbeat(self) -> dict:
        ec_shards = []
        for loc in self.locations:
            for ev in loc.ec_volumes.values():
                h = self.ec_read_heat.get(ev.id)
                ec_shards.append(
                    {
                        "id": ev.id,
                        "collection": ev.collection,
                        "ec_index_bits": sum(1 << sid for sid in ev.shard_ids()),
                        "read_heat": round(h.value(), 3) if h else 0.0,
                        "corrupt_shards": sorted(
                            self.corrupt_shards.get(ev.id, ())
                        ),
                    }
                )
        return {"ip": self.ip, "port": self.port, "ec_shards": ec_shards}

    def close(self) -> None:
        for loc in self.locations:
            loc.close()
