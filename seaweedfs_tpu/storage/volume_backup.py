"""Incremental volume backup by raw .dat tail copy.

Reference: `weed/storage/volume_backup.go` (`IncrementalBackup`) +
`weed/command/backup.go` (`runBackup`): the local copy is a byte-for-byte
prefix of the source volume. Each run

1. compares the source's compaction revision with the local superblock —
   on mismatch the local copy is wiped and re-copied from offset 0 (the
   reference's "compaction occurred, switch to the new revision" path),
2. appends raw `.dat` bytes from the local size to the source's EOF in
   bounded pages (VolumeIncrementalCopy rpc semantics), and
3. rebuilds the needle-map entries for the newly copied region only
   (ScanVolumeFileFrom + VolumeFileScanner4GenIdx: size>0 records are
   puts, size-0 records are tombstones).

Byte-verbatim copying sidesteps needle-level replay entirely: timestamps,
tombstones, and zero-length files are preserved exactly, and a run that
transfers nothing leaves the local copy untouched — repeated runs converge.
"""

from __future__ import annotations

import os

from ..server.http_util import http_bytes, http_bytes_headers, http_json
from ..util.parsers import tolerant_uint
from .needle import Needle, parse_needle_header
from .needle import NEEDLE_HEADER_SIZE  # re-exported there
from .volume import Volume, volume_file_name

PAGE_BYTES = 8 * 1024 * 1024


def parse_tail_frames(blob: bytes, version: int) -> list[Needle]:
    """Decode the framed needle stream of /admin/tail (VolumeTailSender)."""
    out = []
    pos = 0
    while pos + 4 <= len(blob):
        ln = int.from_bytes(blob[pos : pos + 4], "big")
        pos += 4
        rec = blob[pos : pos + ln]
        pos += ln
        _, _, size = parse_needle_header(rec[:NEEDLE_HEADER_SIZE])
        out.append(Needle.from_bytes(rec, size, version))
    return out


def backup_volume(
    master_url: str, vid: int, directory: str, collection: str = ""
) -> dict:
    """One incremental backup pass. Returns counters."""
    r = http_json("GET", f"http://{master_url}/dir/lookup?volumeId={vid}")
    locs = r.get("locations", [])
    if not locs:
        raise RuntimeError(f"volume {vid} not found on any server")
    src = locs[0]["url"]
    st = http_json("GET", f"http://{src}/admin/volume_status?volume={vid}")
    if st.get("error"):
        raise RuntimeError(f"volume status from {src}: {st['error']}")

    base = volume_file_name(directory, collection, vid)
    os.makedirs(directory, exist_ok=True)
    wiped = False
    if os.path.exists(base + ".dat"):
        local = Volume(directory, collection, vid, create_if_missing=False)
        local_rev = local.super_block.compaction_revision
        local.close()
        if local_rev != st["compaction_revision"]:
            # source was compacted since our last pass: our bytes are no
            # longer a prefix of its .dat — start over (volume_backup.go
            # compaction revision mismatch → full copy)
            for ext in (".dat", ".idx"):
                if os.path.exists(base + ext):
                    # sweedlint: ok durability revision-mismatch wipe; a crash mid-wipe re-detects and re-wipes next pass
                    os.unlink(base + ext)
            wiped = True

    start = os.path.getsize(base + ".dat") if os.path.exists(base + ".dat") else 0
    if start == 0 and os.path.exists(base + ".idx"):
        # sweedlint: ok durability stale index with no .dat; next pass rebuilds from zero
        os.unlink(base + ".idx")
    if start:
        # Resume from the last INDEXED record, not the raw .dat size: a
        # previous run may have crashed after fsyncing copied bytes but
        # before _index_region ran. Those unindexed tail bytes are cut and
        # re-copied so every backup byte always has an index entry.
        indexed_end = _indexed_end(base)
        if indexed_end < start:
            with open(base + ".dat", "r+b") as f:
                f.truncate(indexed_end)
            start = indexed_end
    copied = 0
    start_rev = st["compaction_revision"]
    with open(base + ".dat", "ab") as f:
        offset = start
        while True:
            status, page, hdrs = http_bytes_headers(
                "GET",
                f"http://{src}/admin/incremental_copy?volume={vid}"
                f"&offset={offset}&max_bytes={PAGE_BYTES}",
            )
            if status != 200:
                raise RuntimeError(f"incremental copy from {src}: HTTP {status}")
            # a vacuum committing mid-run rewrites the source .dat: bytes at
            # these offsets are no longer a prefix of our copy. Abort before
            # appending garbage; the next run's revision check wipes and
            # restarts from 0 (volume_backup.go revision fencing per page).
            page_rev = tolerant_uint(
                hdrs.get("X-Compaction-Revision", start_rev), start_rev
            )
            if page_rev != start_rev:
                # bytes copied this run straddle revisions — drop them all,
                # leaving the local copy exactly as before the run
                f.truncate(start)
                f.flush()
                os.fsync(f.fileno())
                raise RuntimeError(
                    f"volume {vid} compacted mid-backup "
                    f"(revision {start_rev} -> {page_rev}); rerun to restart"
                )
            if not page:
                break
            f.write(page)
            offset += len(page)
            copied += len(page)
        f.flush()
        os.fsync(f.fileno())

    # Index the new region BEFORE opening the Volume: size-0 records are
    # tombstones (VolumeFileScanner4GenIdx semantics — the reference makes
    # the same size==0 ⇒ delete call). Volume.__init__ truncates any .dat
    # tail past the last indexed record, so the .idx entries must land first.
    writes = deletes = 0
    fresh = start == 0  # Volume.__init__ rebuilds the whole .idx in this case
    if not fresh and copied:
        writes, deletes = _index_region(base, start)
    local = Volume(directory, collection, vid, create_if_missing=False)
    try:
        if fresh:
            writes = local.file_count()
            deletes = local.deleted_count()
        return {
            "volume": vid,
            "from": src,
            "start_offset": start,
            "copied_bytes": copied,
            "writes": writes,
            "deletes": deletes,
            "wiped": wiped,
            "file_count": local.file_count(),
        }
    finally:
        local.close()


def _read_super_block(base: str):
    import struct

    from .super_block import SUPER_BLOCK_SIZE, SuperBlock

    with open(base + ".dat", "rb") as f:
        head = f.read(SUPER_BLOCK_SIZE)
        extra = struct.unpack(">H", head[6:8])[0]
        return SuperBlock.from_bytes(head + f.read(extra))


def _indexed_end(base: str) -> int:
    """End offset of the last record the .idx knows about (appends are
    in offset order, so the last entry is the highest)."""
    from . import idx as idx_mod
    from .needle import get_actual_size

    sb = _read_super_block(base)
    if not os.path.exists(base + ".idx"):
        return sb.block_size()
    entry_size = 8 + idx_mod.OFFSET_SIZE + 4
    idx_size = os.path.getsize(base + ".idx")
    idx_size -= idx_size % entry_size
    if idx_size == 0:
        return sb.block_size()
    with open(base + ".idx", "rb") as f:
        f.seek(idx_size - entry_size)
        _, aoff, size = idx_mod.unpack_entry(f.read(entry_size))
    return aoff + get_actual_size(max(size, 0), sb.version)


def _index_region(base: str, start: int) -> tuple[int, int]:
    """Append .idx entries for every record at offset ≥ start in the .dat
    (ScanVolumeFileFrom + GenIdx). Returns (writes, deletes)."""
    from . import idx as idx_mod
    from .needle import needle_body_length

    writes = deletes = 0
    sb = _read_super_block(base)
    with open(base + ".dat", "rb") as f, open(base + ".idx", "ab") as out:
        version = sb.version
        fsize = os.path.getsize(base + ".dat")
        offset = max(start, sb.block_size())
        while offset + NEEDLE_HEADER_SIZE <= fsize:
            f.seek(offset)
            hdr = f.read(NEEDLE_HEADER_SIZE)
            _, nid, nsize = parse_needle_header(hdr)
            body_len = needle_body_length(nsize if nsize > 0 else 0, version)
            total = NEEDLE_HEADER_SIZE + body_len
            if offset + total > fsize:
                break
            if nsize > 0:
                out.write(idx_mod.pack_entry(nid, offset, nsize))
                writes += 1
            else:
                out.write(idx_mod.pack_entry(nid, offset, -1))
                deletes += 1
            offset += total
    return writes, deletes
