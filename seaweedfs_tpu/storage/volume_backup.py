"""Incremental volume backup by append-timestamp tail (reference
`weed/storage/volume_backup.go` + `weed/command/backup.go`): a local copy
volume tracks its own last_append_at_ns; each run fetches only records
appended since then and replays them — size-0 tombstones as deletes,
everything else as timestamp-preserving writes — so repeated runs converge
and resume."""

from __future__ import annotations

from ..server.http_util import http_bytes, http_json
from .needle import Needle, parse_needle_header
from .needle import NEEDLE_HEADER_SIZE  # re-exported there
from .volume import Volume


def parse_tail_frames(blob: bytes, version: int) -> list[Needle]:
    out = []
    pos = 0
    while pos + 4 <= len(blob):
        ln = int.from_bytes(blob[pos : pos + 4], "big")
        pos += 4
        rec = blob[pos : pos + ln]
        pos += ln
        _, _, size = parse_needle_header(rec[:NEEDLE_HEADER_SIZE])
        out.append(Needle.from_bytes(rec, size, version))
    return out


def backup_volume(
    master_url: str, vid: int, directory: str, collection: str = ""
) -> dict:
    """One incremental backup pass. Returns counters."""
    r = http_json("GET", f"http://{master_url}/dir/lookup?volumeId={vid}")
    locs = r.get("locations", [])
    if not locs:
        raise RuntimeError(f"volume {vid} not found on any server")
    src = locs[0]["url"]
    local = Volume(directory, collection, vid)
    try:
        since = local.last_append_at_ns
        status, blob = http_bytes(
            "GET", f"http://{src}/admin/tail?volume={vid}&since_ns={since}"
        )
        if status != 200:
            raise RuntimeError(f"tail from {src}: HTTP {status}")
        writes = deletes = 0
        for n in parse_tail_frames(blob, local.version):
            if n.size == 0 and not n.data:
                local.delete_needle(n, append_at_ns=n.append_at_ns)
                deletes += 1
            else:
                local.write_needle(n, append_at_ns=n.append_at_ns)
                writes += 1
        local.sync()
        return {
            "volume": vid,
            "from": src,
            "since_ns": since,
            "writes": writes,
            "deletes": deletes,
            "file_count": local.file_count(),
        }
    finally:
        local.close()
