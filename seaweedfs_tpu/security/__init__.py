"""Security: per-fid JWT write/read auth + IP whitelist guard
(reference: `weed/security/jwt.go`, `guard.go`).

The master signs a short-lived fid-scoped token into every assign response;
volume servers verify it on writes (and on reads when a read key is set).
Keys are shared secrets (HS256), distributed via config — mirroring
`security.toml` [jwt.signing] / [jwt.signing.read].
"""

from .jwt import decode_jwt, gen_jwt, read_auth_query, verify_fid_jwt  # noqa: F401
from .guard import Guard  # noqa: F401
