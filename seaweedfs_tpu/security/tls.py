"""TLS / mTLS contexts for the HTTP plane.

Mirrors `weed/security/tls.go`: servers load a cert/key pair and — when a
CA is configured — require and verify client certificates
(`tls.go:22,37` RequireAndVerifyClientCert); clients present their own
pair and pin the cluster CA. Certificate paths come from security.toml:

    [tls]
    ca = "/etc/seaweedfs/ca.crt"          # enables mTLS when set

    [tls.master]   # per-component pairs, like [grpc.master] in the
    cert = ""      # reference's security.toml
    key = ""

    [tls.volume]
    cert = ""
    key = ""

    [tls.client]
    cert = ""
    key = ""

Gateways (s3/webdav) also accept -cert.file/-key.file flags directly,
matching `weed s3 -cert.file` (`command/s3.go:42`).
"""

from __future__ import annotations

import ssl
from typing import Optional


def server_context(
    cert_file: str, key_file: str = "", ca_file: str = ""
) -> ssl.SSLContext:
    """TLS termination; with ca_file, clients must present a CA-signed
    certificate (mTLS). An empty key_file means a combined cert+key PEM."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file or cert_file)
    if ca_file:
        ctx.load_verify_locations(ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def optional_server_context(
    cert_file: str, key_file: str = "", ca_file: str = ""
) -> Optional[ssl.SSLContext]:
    """(cert, key, ca) from flags/config → context, None when all empty
    (plaintext). key/ca WITHOUT a cert is a misconfiguration — refusing is
    safer than silently starting plaintext with the CA ignored."""
    if not (cert_file or key_file or ca_file):
        return None
    if not cert_file:
        raise ValueError(
            "TLS misconfigured: -key.file/-caCert.file given without "
            "-cert.file (refusing to start plaintext)"
        )
    return server_context(cert_file, key_file, ca_file)


def client_context(
    ca_file: str = "",
    cert_file: str = "",
    key_file: str = "",
    insecure: bool = False,
) -> ssl.SSLContext:
    """Pinned-CA (and optionally client-cert) https context. Without a CA
    the SYSTEM trust store verifies the server; disabling verification is
    explicit opt-in only — a client cert with no CA must not silently
    accept any server (MITM)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if ca_file:
        ctx.load_verify_locations(ca_file)
    elif insecure:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    else:
        ctx.load_default_certs()
    if cert_file:
        ctx.load_cert_chain(cert_file, key_file or cert_file)
    return ctx
