"""Compact HS256 JWT, stdlib-only (reference `security/jwt.go`:
GenJwt signs {exp, fid}; volume servers verify the token covers the fid
being written)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional

_HEADER = {"alg": "HS256", "typ": "JWT"}


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def gen_jwt(signing_key: str, fid: str, expires_seconds: int = 10) -> str:
    """Short-lived token scoped to one fid (jwt.go GenJwt, default 10s)."""
    header = _b64(json.dumps(_HEADER, separators=(",", ":")).encode())
    payload = _b64(
        json.dumps(
            {"exp": int(time.time()) + expires_seconds, "fid": fid},
            separators=(",", ":"),
        ).encode()
    )
    msg = f"{header}.{payload}"
    sig = _b64(
        hmac.new(signing_key.encode(), msg.encode(), hashlib.sha256).digest()
    )
    return f"{msg}.{sig}"


def decode_jwt(signing_key: str, token: str) -> Optional[dict]:
    """Signature + expiry check; returns claims or None."""
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        return None
    msg = f"{header}.{payload}"
    want = _b64(
        hmac.new(signing_key.encode(), msg.encode(), hashlib.sha256).digest()
    )
    if not hmac.compare_digest(want, sig):
        return None
    try:
        claims = json.loads(_unb64(payload))
    except (ValueError, json.JSONDecodeError):
        return None
    if claims.get("exp", 0) < time.time():
        return None
    return claims


def verify_fid_jwt(signing_key: str, token: str, fid: str) -> bool:
    """The token must be valid AND cover this exact fid (jwt.go:60)."""
    claims = decode_jwt(signing_key, token)
    if claims is None:
        return False
    # normalize "vid,key_cookie" vs "vid/key_cookie"
    return claims.get("fid", "").replace("/", ",") == fid.replace("/", ",")


def read_auth_query(signing_key: str, fid: str) -> str:
    """'?auth=<token>' query suffix for a fid-scoped read, or '' when the
    deployment runs open — the one spelling every read client shares."""
    if not signing_key:
        return ""
    return "?auth=" + gen_jwt(signing_key, fid)
