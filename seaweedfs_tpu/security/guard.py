"""IP whitelist guard (reference `security/guard.go`): exact IPs, CIDR
prefixes, or "*" wildcard; empty whitelist = allow everyone."""

from __future__ import annotations

import ipaddress


class Guard:
    def __init__(self, whitelist: list[str] | None = None):
        self.networks: list[ipaddress._BaseNetwork] = []
        self.exact: set[str] = set()
        self.allow_all = not whitelist
        for item in whitelist or []:
            if item == "*":
                self.allow_all = True
            elif "/" in item:
                self.networks.append(ipaddress.ip_network(item, strict=False))
            else:
                self.exact.add(item)

    def allowed(self, ip: str) -> bool:
        if self.allow_all:
            return True
        if ip in self.exact:
            return True
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)
