"""Dirty-page interval buffering for mounted file writes.

Reference: `weed/filesys/dirty_page_interval.go` (ContinuousIntervals:
overlapping writes are clipped against existing intervals, adjacent ones
merged) and `dirty_pages.go` (flush when a continuous run reaches the
chunk size). Random writes at arbitrary offsets coalesce into the fewest
possible upload chunks.
"""

from __future__ import annotations

from typing import Optional


class Interval:
    __slots__ = ("start", "data", "ts_ns")

    def __init__(self, start: int, data: bytes, ts_ns: int = 0):
        self.start = start
        self.data = data
        self.ts_ns = ts_ns

    @property
    def stop(self) -> int:
        return self.start + len(self.data)

    def __repr__(self):
        return f"Interval({self.start}..{self.stop})"


class ContinuousIntervals:
    """Sorted, non-overlapping dirty byte ranges of one open file."""

    def __init__(self):
        self.intervals: list[Interval] = []

    def total_size(self) -> int:
        return sum(len(i.data) for i in self.intervals)

    def add_interval(self, offset: int, data: bytes, ts_ns: int = 0) -> None:
        """Newest write wins; older intervals are clipped around it
        (dirty_page_interval.go AddInterval)."""
        if not data:
            return
        new = Interval(offset, bytes(data), ts_ns)
        out: list[Interval] = []
        for iv in self.intervals:
            if iv.stop <= new.start or iv.start >= new.stop:
                out.append(iv)
                continue
            # clip the old interval against the new one
            if iv.start < new.start:
                out.append(Interval(iv.start, iv.data[: new.start - iv.start], iv.ts_ns))
            if iv.stop > new.stop:
                out.append(Interval(new.stop, iv.data[new.stop - iv.start :], iv.ts_ns))
        out.append(new)
        out.sort(key=lambda i: i.start)
        # merge adjacent runs so flush produces the fewest chunks
        merged: list[Interval] = []
        for iv in out:
            if merged and merged[-1].stop == iv.start:
                prev = merged[-1]
                merged[-1] = Interval(
                    prev.start, prev.data + iv.data, max(prev.ts_ns, iv.ts_ns)
                )
            else:
                merged.append(iv)
        self.intervals = merged

    def read_data_at(self, offset: int, size: int) -> list[tuple[int, bytes]]:
        """Dirty bytes overlapping [offset, offset+size) as
        (absolute_offset, data) pairs."""
        out = []
        stop = offset + size
        for iv in self.intervals:
            if iv.stop <= offset or iv.start >= stop:
                continue
            lo = max(iv.start, offset)
            hi = min(iv.stop, stop)
            out.append((lo, iv.data[lo - iv.start : hi - iv.start]))
        return out

    def pop_all(self) -> list[Interval]:
        ivs, self.intervals = self.intervals, []
        return ivs

    def max_stop(self) -> int:
        return max((i.stop for i in self.intervals), default=0)

    def pop_largest_if_over(self, limit: int) -> Optional[Interval]:
        """Detach the largest continuous run if it has reached `limit`
        (eager flush of full chunks, dirty_pages.go saveExistingLargestPageToStorage)."""
        if not self.intervals:
            return None
        largest = max(self.intervals, key=lambda i: len(i.data))
        if len(largest.data) < limit:
            return None
        self.intervals.remove(largest)
        return largest
