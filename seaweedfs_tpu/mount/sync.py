"""Local-directory synchronization over the filer — the mount daemon.

Reference: `weed/command/mount_std.go` exposes the filer through FUSE; in
this build the same continuous view is provided by a bidirectional
synchronizer: remote metadata events (the stream that keeps the
reference's meta_cache fresh) are applied to a local directory, and local
modifications (mtime/size scan) are written back through WFS. `weed
filer.copy` (command/filer_copy.go) is the one-shot upload variant.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..filer.client import FilerClient
from ..util import glog
from .wfs import WFS


def copy_to_filer(
    local_dir: str,
    filer_url: str,
    dest_dir: str = "/",
    chunk_size: int = 8 * 1024 * 1024,
) -> int:
    """Upload a local tree (weed filer.copy). Returns files copied."""
    wfs = WFS(filer_url, chunk_size=chunk_size, use_meta_cache=False)
    count = 0
    try:
        dest_dir = "/" + dest_dir.strip("/")
        for root, dirs, files in os.walk(local_dir):
            rel = os.path.relpath(root, local_dir)
            remote_root = (
                dest_dir if rel == "." else f"{dest_dir.rstrip('/')}/{rel}"
            ).replace("//", "/")
            for d in dirs:
                wfs.mkdir(f"{remote_root.rstrip('/')}/{d}")
            for name in files:
                src = os.path.join(root, name)
                with open(src, "rb") as f, wfs.open(
                    f"{remote_root.rstrip('/')}/{name}", "w"
                ) as out:
                    off = 0
                    while True:
                        piece = f.read(chunk_size)
                        if not piece:
                            break
                        out.write(off, piece)
                        off += len(piece)
                count += 1
        return count
    finally:
        wfs.close()


def copy_from_filer(
    filer_url: str, src_dir: str, local_dir: str, chunk_size: int = 8 * 1024 * 1024
) -> int:
    """Materialize a filer tree locally. Returns files copied."""
    wfs = WFS(filer_url, chunk_size=chunk_size, use_meta_cache=False)
    count = 0
    try:
        def walk(remote: str, local: str):
            nonlocal count
            os.makedirs(local, exist_ok=True)
            for e in wfs.listdir(remote):
                lpath = os.path.join(local, e.name)
                if e.is_directory:
                    walk(e.full_path, lpath)
                else:
                    with wfs.open(e.full_path, "r") as f, open(lpath, "wb") as out:
                        off, size = 0, f.size()
                        while off < size:
                            piece = f.read(off, min(chunk_size, size - off))
                            if not piece:
                                break
                            out.write(piece)
                            off += len(piece)
                    count += 1

        walk("/" + src_dir.strip("/"), local_dir)
        return count
    finally:
        wfs.close()


class MountSync:
    """Continuous bidirectional sync between a local dir and a filer dir.

    Remote→local rides the filer metadata event feed; local→remote is an
    mtime/size scan. A state file records (mtime, size) per path at the
    last sync so each side only pushes genuine changes (and remote events
    caused by our own uploads are recognized and skipped).
    """

    def __init__(
        self,
        filer_url: str,
        remote_dir: str,
        local_dir: str,
        scan_seconds: float = 1.0,
    ):
        self.client = FilerClient(filer_url)
        self.wfs = WFS(filer_url, use_meta_cache=False)
        self.remote_dir = "/" + remote_dir.strip("/")
        self.local_dir = local_dir
        self.scan_seconds = scan_seconds
        self._state_path = os.path.join(local_dir, ".weed_mount_state.json")
        self._state: dict[str, list] = {}
        self._last_ts_ns = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MountSync":
        os.makedirs(self.local_dir, exist_ok=True)
        if os.path.exists(self._state_path):
            with open(self._state_path) as f:
                saved = json.load(f)
            self._state = saved.get("state", {})
            self._last_ts_ns = saved.get("last_ts_ns", 0)
        else:
            self._last_ts_ns = time.time_ns()
            copy_from_filer(
                self.client.base.split("//", 1)[1],
                self.remote_dir,
                self.local_dir,
            )
            for rel, st in self._scan_local().items():
                self._state[rel] = st
        self._save_state()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5.0)
        self.wfs.close()

    def _save_state(self) -> None:
        with open(self._state_path, "w") as f:
            json.dump({"state": self._state, "last_ts_ns": self._last_ts_ns}, f)

    # -- helpers -------------------------------------------------------------
    def _rel_of_remote(self, full_path: str) -> Optional[str]:
        prefix = self.remote_dir.rstrip("/") + "/"
        if self.remote_dir == "/":
            prefix = "/"
        if not full_path.startswith(prefix):
            return None
        return full_path[len(prefix) :]

    def _remote_of_rel(self, rel: str) -> str:
        return f"{self.remote_dir.rstrip('/')}/{rel}".replace("//", "/")

    def _scan_local(self) -> dict[str, list]:
        out = {}
        for root, _dirs, files in os.walk(self.local_dir):
            for name in files:
                if name == ".weed_mount_state.json":
                    continue
                p = os.path.join(root, name)
                rel = os.path.relpath(p, self.local_dir)
                st = os.stat(p)
                out[rel] = [st.st_mtime, st.st_size]
        return out

    # -- the sync loop -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.scan_seconds):
            try:
                self.sync_once()
            except Exception as e:
                glog.V(1).info("sync pass failed: %s", e)
                continue

    def sync_once(self) -> dict:
        pulled = self._apply_remote_events()
        pushed = self._push_local_changes()
        self._save_state()
        return {"pulled": pulled, "pushed": pushed}

    @staticmethod
    def _remote_version(entry_dict: dict) -> int:
        """ns-resolution change marker for a remote entry: the newest chunk
        mtime, falling back to the (second-resolution) entry mtime."""
        return max(
            (c.get("mtime", 0) for c in entry_dict.get("chunks", [])),
            default=entry_dict.get("mtime", 0) * 1_000_000_000,
        )

    def _apply_remote_events(self) -> int:
        r = self.client.meta_events(since_ns=self._last_ts_ns)
        applied = 0
        for e in r.get("events", ()):
            # one bad event (e.g. create of an already-deleted file) must not
            # wedge the feed: apply best-effort, always advance past it
            try:
                applied += self._apply_one_remote_event(e)
            except Exception as e:
                glog.V(2).info("remote event skipped: %s", e)
        self._last_ts_ns = r.get("last_ts_ns", self._last_ts_ns)
        return applied

    def _apply_one_remote_event(self, e: dict) -> int:
        applied = 0
        old, new = e.get("old_entry"), e.get("new_entry")
        if old and (not new or new["full_path"] != old["full_path"]):
            rel = self._rel_of_remote(old["full_path"])
            if rel is not None:
                lp = os.path.join(self.local_dir, rel)
                if os.path.isfile(lp):
                    os.unlink(lp)
                    self._state.pop(rel, None)
                    applied += 1
        if new and not new.get("is_directory"):
            rel = self._rel_of_remote(new["full_path"])
            if rel is None:
                return applied
            lp = os.path.join(self.local_dir, rel)
            # skip events at or before the remote version we already hold
            # (echoes of our own pushes, or replays)
            known = self._state.get(rel)
            version = self._remote_version(new)
            if (
                known
                and len(known) >= 3
                and os.path.exists(lp)
                and version <= known[2]
            ):
                return applied
            os.makedirs(os.path.dirname(lp) or ".", exist_ok=True)
            with self.wfs.open(new["full_path"], "r") as f, open(lp, "wb") as out:
                off, total = 0, f.size()
                while off < total:
                    piece = f.read(off, min(4 * 1024 * 1024, total - off))
                    if not piece:
                        break
                    out.write(piece)
                    off += len(piece)
            st = os.stat(lp)
            self._state[rel] = [st.st_mtime, st.st_size, version]
            applied += 1
        return applied

    def _push_local_changes(self) -> int:
        now = self._scan_local()
        pushed = 0
        for rel, st in now.items():
            known = self._state.get(rel)
            if known and known[:2] == st:
                continue
            lp = os.path.join(self.local_dir, rel)
            remote = self._remote_of_rel(rel)
            with open(lp, "rb") as f, self.wfs.open(remote, "w") as out:
                off = 0
                while True:
                    piece = f.read(4 * 1024 * 1024)
                    if not piece:
                        break
                    out.write(off, piece)
                    off += len(piece)
            # record the remote version our push produced so its event
            # echo is recognized and skipped
            d = self.client.get_entry(remote)
            version = self._remote_version(d) if d else 0
            self._state[rel] = [st[0], st[1], version]
            pushed += 1
        for rel in list(self._state):
            if rel not in now:
                # local deletion → remote deletion
                self.client.delete(self._remote_of_rel(rel))
                self._state.pop(rel, None)
                pushed += 1
        return pushed
