"""Kernel-visible FUSE mount over the WFS ops (weed mount).

The reference mounts the filer as a real filesystem through bazil.org/fuse
(`weed/filesys/wfs.go:55`, `weed/command/mount_std.go:51`) so unmodified
programs (`ls`, `cp`, editors) work against the store.  This module does the
same through a ctypes binding of libfuse 2.x (the runtime .so ships on
stock Linux; no Python fuse package is required): each FUSE callback maps
onto the existing `mount.wfs.WFS` operations, which already carry the meta
cache, chunked uploads, and the filer's cipher setting.

Gating: `fuse_available()` is False when libfuse/`/dev/fuse` are absent —
callers (CLI, tests) fall back to the FUSE-less sync daemon (mount/sync.py).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import stat as stat_mod
import subprocess
import threading
import time
from typing import Optional

from ..util import glog
from .wfs import WFS, FileHandle

# -- libfuse 2.x ABI ---------------------------------------------------------

c_void_p = ctypes.c_void_p
c_char_p = ctypes.c_char_p
c_int = ctypes.c_int
c_uint = ctypes.c_uint
c_size_t = ctypes.c_size_t
c_off_t = ctypes.c_longlong
c_mode_t = ctypes.c_uint
c_dev_t = ctypes.c_ulonglong
c_uid_t = ctypes.c_uint
c_gid_t = ctypes.c_uint


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):
    """x86_64 linux struct stat."""

    _fields_ = [
        ("st_dev", ctypes.c_ulong),
        ("st_ino", ctypes.c_ulong),
        ("st_nlink", ctypes.c_ulong),
        ("st_mode", c_mode_t),
        ("st_uid", c_uid_t),
        ("st_gid", c_gid_t),
        ("__pad0", ctypes.c_int),
        ("st_rdev", ctypes.c_ulong),
        ("st_size", ctypes.c_long),
        ("st_blksize", ctypes.c_long),
        ("st_blocks", ctypes.c_long),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__glibc_reserved", ctypes.c_long * 3),
    ]


class FuseFileInfo(ctypes.Structure):
    """libfuse 2.9 struct fuse_file_info."""

    _fields_ = [
        ("flags", c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", c_int),
        ("bits", c_uint),  # direct_io/keep_cache/... bitfield
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


_GETATTR = ctypes.CFUNCTYPE(c_int, c_char_p, ctypes.POINTER(Stat))
_READLINK = ctypes.CFUNCTYPE(c_int, c_char_p, c_char_p, c_size_t)
_GETDIR = c_void_p  # deprecated slot
_MKNOD = ctypes.CFUNCTYPE(c_int, c_char_p, c_mode_t, c_dev_t)
_MKDIR = ctypes.CFUNCTYPE(c_int, c_char_p, c_mode_t)
_UNLINK = ctypes.CFUNCTYPE(c_int, c_char_p)
_RMDIR = ctypes.CFUNCTYPE(c_int, c_char_p)
_SYMLINK = ctypes.CFUNCTYPE(c_int, c_char_p, c_char_p)
_RENAME = ctypes.CFUNCTYPE(c_int, c_char_p, c_char_p)
_LINK = ctypes.CFUNCTYPE(c_int, c_char_p, c_char_p)
_CHMOD = ctypes.CFUNCTYPE(c_int, c_char_p, c_mode_t)
_CHOWN = ctypes.CFUNCTYPE(c_int, c_char_p, c_uid_t, c_gid_t)
_TRUNCATE = ctypes.CFUNCTYPE(c_int, c_char_p, c_off_t)
_UTIME = c_void_p  # deprecated slot
_OPEN = ctypes.CFUNCTYPE(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))
_READ = ctypes.CFUNCTYPE(
    c_int, c_char_p, ctypes.POINTER(ctypes.c_char), c_size_t, c_off_t,
    ctypes.POINTER(FuseFileInfo),
)
_WRITE = ctypes.CFUNCTYPE(
    c_int, c_char_p, ctypes.POINTER(ctypes.c_char), c_size_t, c_off_t,
    ctypes.POINTER(FuseFileInfo),
)
_STATFS = ctypes.CFUNCTYPE(c_int, c_char_p, c_void_p)
_FLUSH = ctypes.CFUNCTYPE(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))
_RELEASE = ctypes.CFUNCTYPE(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))
_FSYNC = ctypes.CFUNCTYPE(c_int, c_char_p, c_int, ctypes.POINTER(FuseFileInfo))
_FILL_DIR = ctypes.CFUNCTYPE(
    c_int, c_void_p, c_char_p, ctypes.POINTER(Stat), c_off_t
)
_READDIR = ctypes.CFUNCTYPE(
    c_int, c_char_p, c_void_p, _FILL_DIR, c_off_t,
    ctypes.POINTER(FuseFileInfo),
)
_INIT = ctypes.CFUNCTYPE(c_void_p, c_void_p)
_DESTROY = ctypes.CFUNCTYPE(None, c_void_p)
_ACCESS = ctypes.CFUNCTYPE(c_int, c_char_p, c_int)
_CREATE = ctypes.CFUNCTYPE(
    c_int, c_char_p, c_mode_t, ctypes.POINTER(FuseFileInfo)
)
_FTRUNCATE = ctypes.CFUNCTYPE(
    c_int, c_char_p, c_off_t, ctypes.POINTER(FuseFileInfo)
)
_FGETATTR = ctypes.CFUNCTYPE(
    c_int, c_char_p, ctypes.POINTER(Stat), ctypes.POINTER(FuseFileInfo)
)
_UTIMENS = ctypes.CFUNCTYPE(c_int, c_char_p, ctypes.POINTER(Timespec * 2))
_SETXATTR = ctypes.CFUNCTYPE(
    c_int, c_char_p, c_char_p, ctypes.POINTER(ctypes.c_char), c_size_t, c_int
)
_GETXATTR = ctypes.CFUNCTYPE(
    c_int, c_char_p, c_char_p, ctypes.POINTER(ctypes.c_char), c_size_t
)
_LISTXATTR = ctypes.CFUNCTYPE(
    c_int, c_char_p, ctypes.POINTER(ctypes.c_char), c_size_t
)
_REMOVEXATTR = ctypes.CFUNCTYPE(c_int, c_char_p, c_char_p)


class FuseOperations(ctypes.Structure):
    """libfuse 2.9 struct fuse_operations (field order is the ABI)."""

    _fields_ = [
        ("getattr", _GETATTR),
        ("readlink", _READLINK),
        ("getdir", _GETDIR),
        ("mknod", _MKNOD),
        ("mkdir", _MKDIR),
        ("unlink", _UNLINK),
        ("rmdir", _RMDIR),
        ("symlink", _SYMLINK),
        ("rename", _RENAME),
        ("link", _LINK),
        ("chmod", _CHMOD),
        ("chown", _CHOWN),
        ("truncate", _TRUNCATE),
        ("utime", _UTIME),
        ("open", _OPEN),
        ("read", _READ),
        ("write", _WRITE),
        ("statfs", _STATFS),
        ("flush", _FLUSH),
        ("release", _RELEASE),
        ("fsync", _FSYNC),
        ("setxattr", _SETXATTR),
        ("getxattr", _GETXATTR),
        ("listxattr", _LISTXATTR),
        ("removexattr", _REMOVEXATTR),
        ("opendir", c_void_p),
        ("readdir", _READDIR),
        ("releasedir", c_void_p),
        ("fsyncdir", c_void_p),
        ("init", _INIT),
        ("destroy", _DESTROY),
        ("access", _ACCESS),
        ("create", _CREATE),
        ("ftruncate", _FTRUNCATE),
        ("fgetattr", _FGETATTR),
        ("lock", c_void_p),
        ("utimens", _UTIMENS),
        ("bmap", c_void_p),
        ("flags", c_uint),  # nullpath_ok etc. bitfield word
        ("ioctl", c_void_p),
        ("poll", c_void_p),
        ("write_buf", c_void_p),
        ("read_buf", c_void_p),
        ("flock", c_void_p),
        ("fallocate", c_void_p),
    ]


def _find_libfuse() -> Optional[str]:
    for cand in (ctypes.util.find_library("fuse"), "libfuse.so.2"):
        if not cand:
            continue
        try:
            ctypes.CDLL(cand)
            return cand
        except OSError:
            continue
    return None


def fuse_available() -> bool:
    # the Stat/FuseFileInfo ctypes layouts below encode the x86_64 Linux
    # ABI; on other arches (aarch64 reorders struct stat fields) a mount
    # would come up and then feed the kernel garbage metadata — fall back
    # to the sync daemon there instead
    import platform

    return (
        platform.machine() == "x86_64"
        and _find_libfuse() is not None
        and os.path.exists("/dev/fuse")
    )


class FuseMount:
    """Mount a WFS (filer view) at a local mountpoint through libfuse2.

    The event loop runs on a dedicated thread (single-threaded FUSE loop:
    `-s` — the WFS meta cache and filer client are the shared state, and
    the Python side is GIL-serialized anyway).  `unmount()` (or the process
    exiting) detaches via fusermount -u.
    """

    def __init__(self, wfs: WFS, mountpoint: str, allow_other: bool = False,
                 root: str = "/"):
        lib = _find_libfuse()
        if lib is None:
            raise RuntimeError("libfuse 2.x not found")
        # filer sub-tree exposed at the mountpoint (weed mount -filer.path)
        self.root = "/" + root.strip("/") if root.strip("/") else ""
        self._lib = ctypes.CDLL(lib)
        self._lib.fuse_main_real.restype = c_int
        self._lib.fuse_main_real.argtypes = [
            c_int, ctypes.POINTER(c_char_p), ctypes.POINTER(FuseOperations),
            c_size_t, c_void_p,
        ]
        self.wfs = wfs
        self.mountpoint = os.path.abspath(mountpoint)
        self.allow_other = allow_other
        self._handles: dict[int, FileHandle] = {}
        self._next_fh = 1
        self._hlock = threading.Lock()
        self._ops = self._build_ops()  # keep callbacks alive
        self._thread: Optional[threading.Thread] = None
        self._rc: Optional[int] = None

    def _fp(self, path: bytes) -> str:
        """Kernel path → filer path under the mounted sub-tree."""
        p = path.decode()
        if not self.root:
            return p
        return self.root if p == "/" else self.root + p

    def _commit_entry(self, path: str, entry) -> None:
        """Persist changed metadata (filer create is an upsert)."""
        self.wfs._commit_meta(path, entry)

    # -- op table -------------------------------------------------------------
    def _build_ops(self) -> FuseOperations:
        def guard(fn):
            def wrapper(*a):
                try:
                    return fn(*a)
                except FileNotFoundError:
                    return -errno.ENOENT
                except FileExistsError:
                    return -errno.EEXIST
                except IsADirectoryError:
                    return -errno.EISDIR
                except NotADirectoryError:
                    return -errno.ENOTDIR
                except PermissionError:
                    return -errno.EACCES
                except OSError as e:
                    return -(e.errno or errno.EIO)
                except Exception:
                    glog.exception("fuse op failed")
                    return -errno.EIO

            return wrapper

        def fill_stat(st, entry) -> None:
            ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(Stat))
            if entry.is_directory:
                st.st_mode = stat_mod.S_IFDIR | (entry.mode & 0o7777)
                st.st_nlink = 2
            else:
                st.st_mode = stat_mod.S_IFREG | (entry.mode & 0o7777)
                st.st_nlink = 1
                st.st_size = entry.file_size()
            st.st_uid = entry.uid or os.getuid()
            st.st_gid = entry.gid or os.getgid()
            st.st_blksize = 4096
            st.st_blocks = (st.st_size + 511) // 512
            st.st_mtim.tv_sec = entry.mtime
            st.st_ctim.tv_sec = entry.crtime or entry.mtime
            st.st_atim.tv_sec = entry.mtime

        @guard
        def op_getattr(path, st):
            p = self._fp(path)
            try:
                entry = self.wfs.stat(p)
            except FileNotFoundError:
                if path != b"/":
                    raise
                # a fresh filer has no "/" entry; the mount root must
                # always stat (the kernel getattrs it while mounting)
                from ..filer.entry import Entry

                entry = Entry(full_path="/", is_directory=True, mode=0o755)
            fill_stat(st.contents, entry)
            return 0

        @guard
        def op_readdir(path, buf, fill, offset, fi):
            fill(buf, b".", None, 0)
            fill(buf, b"..", None, 0)
            for e in self.wfs.listdir(self._fp(path)):
                name = e.full_path.rsplit("/", 1)[-1]
                fill(buf, name.encode(), None, 0)
            return 0

        @guard
        def op_mkdir(path, mode):
            self.wfs.mkdir(self._fp(path), mode & 0o7777)
            return 0

        @guard
        def op_unlink(path):
            self.wfs.unlink(self._fp(path))
            return 0

        @guard
        def op_rmdir(path):
            self.wfs.rmdir(self._fp(path))
            return 0

        @guard
        def op_rename(old, new):
            self.wfs.rename(self._fp(old), self._fp(new))
            return 0

        @guard
        def op_chmod(path, mode):
            p = self._fp(path)
            entry = self.wfs.stat(p)
            entry.mode = mode & 0o7777
            self._commit_entry(p, entry)
            return 0

        @guard
        def op_chown(path, uid, gid):
            p = self._fp(path)
            entry = self.wfs.stat(p)
            if uid != 0xFFFFFFFF:
                entry.uid = uid
            if gid != 0xFFFFFFFF:
                entry.gid = gid
            self._commit_entry(p, entry)
            return 0

        def _register(h: FileHandle) -> int:
            with self._hlock:
                fh = self._next_fh
                self._next_fh += 1
                self._handles[fh] = h
            return fh

        @guard
        def op_create(path, mode, fi):
            p = self._fp(path)
            h = self.wfs.open(p, "w")
            if (mode & 0o7777) != h.entry.mode:
                # WFS.open('w') already committed the entry with the default
                # mode; persist the kernel-requested one or `touch`-style
                # empty creates stat with the wrong permissions
                h.entry.mode = mode & 0o7777
                self._commit_entry(p, h.entry)
            fi.contents.fh = _register(h)
            return 0

        @guard
        def op_open(path, fi):
            flags = fi.contents.flags
            mode = "r"
            if flags & (os.O_WRONLY | os.O_RDWR):
                mode = "r+"
            if flags & os.O_TRUNC:
                mode = "w"
            h = self.wfs.open(self._fp(path), mode)
            fi.contents.fh = _register(h)
            return 0

        @guard
        def op_read(path, buf, size, offset, fi):
            h = self._handles.get(fi.contents.fh)
            if h is None:
                return -errno.EBADF
            data = h.read(offset, size)
            ctypes.memmove(buf, data, len(data))
            return len(data)

        @guard
        def op_write(path, buf, size, offset, fi):
            h = self._handles.get(fi.contents.fh)
            if h is None:
                return -errno.EBADF
            data = ctypes.string_at(buf, size)
            return h.write(offset, data)

        @guard
        def op_truncate(path, length):
            with self.wfs.open(self._fp(path), "r+") as h:
                h.truncate(length)
            return 0

        @guard
        def op_ftruncate(path, length, fi):
            h = self._handles.get(fi.contents.fh)
            if h is None:
                return -errno.EBADF
            h.truncate(length)
            return 0

        @guard
        def op_fgetattr(path, st, fi):
            h = self._handles.get(fi.contents.fh)
            if h is None:
                return -errno.EBADF
            fill_stat(st.contents, h.entry)
            st.contents.st_size = max(st.contents.st_size, h.size())
            return 0

        @guard
        def op_flush(path, fi):
            h = self._handles.get(fi.contents.fh)
            if h is not None:
                h.flush()
            return 0

        @guard
        def op_release(path, fi):
            with self._hlock:
                h = self._handles.pop(fi.contents.fh, None)
            if h is not None:
                h.close()
            return 0

        @guard
        def op_fsync(path, datasync, fi):
            h = self._handles.get(fi.contents.fh)
            if h is not None:
                h.flush()
            return 0

        @guard
        def op_access(path, amode):
            p = self._fp(path)
            if path != b"/" and not self.wfs.exists(p):
                return -errno.ENOENT
            return 0

        @guard
        def op_utimens(path, times):
            p = self._fp(path)
            entry = self.wfs.stat(p)
            if times:
                entry.mtime = times.contents[1].tv_sec or int(time.time())
            else:
                entry.mtime = int(time.time())
            self._commit_entry(p, entry)
            return 0

        @guard
        def op_setxattr(path, name, value, size, flags):
            if name.startswith(b"security."):
                # refused symmetrically with getxattr's fast ENODATA — a
                # stored-but-unreadable attribute would confuse rsync -X
                return -errno.EOPNOTSUPP
            p = self._fp(path)
            data = ctypes.string_at(value, size) if size else b""
            self.wfs.setxattr(
                p, name.decode(), data,
                create=flags == 1, replace=flags == 2,  # XATTR_CREATE/REPLACE
            )
            return 0

        @guard
        def op_getxattr(path, name, buf, size):
            if name == b"security.capability":
                # the kernel probes this before EVERY write; never stored
                # here (file capabilities on a network mount are not a
                # thing), so answer without a filer lookup
                return -errno.ENODATA
            p = self._fp(path)
            raw = self.wfs.getxattr(p, name.decode())
            if size == 0:
                return len(raw)  # probe call: report needed length
            if size < len(raw):
                return -errno.ERANGE
            ctypes.memmove(buf, raw, len(raw))
            return len(raw)

        @guard
        def op_listxattr(path, buf, size):
            p = self._fp(path)
            blob = b"".join(
                n.encode() + b"\x00" for n in self.wfs.listxattr(p)
            )
            if size == 0:
                return len(blob)
            if size < len(blob):
                return -errno.ERANGE
            ctypes.memmove(buf, blob, len(blob))
            return len(blob)

        @guard
        def op_removexattr(path, name):
            self.wfs.removexattr(self._fp(path), name.decode())
            return 0

        ops = FuseOperations()
        ops.setxattr = _SETXATTR(op_setxattr)
        ops.getxattr = _GETXATTR(op_getxattr)
        ops.listxattr = _LISTXATTR(op_listxattr)
        ops.removexattr = _REMOVEXATTR(op_removexattr)
        ops.getattr = _GETATTR(op_getattr)
        ops.mkdir = _MKDIR(op_mkdir)
        ops.unlink = _UNLINK(op_unlink)
        ops.rmdir = _RMDIR(op_rmdir)
        ops.rename = _RENAME(op_rename)
        ops.chmod = _CHMOD(op_chmod)
        ops.chown = _CHOWN(op_chown)
        ops.truncate = _TRUNCATE(op_truncate)
        ops.open = _OPEN(op_open)
        ops.read = _READ(op_read)
        ops.write = _WRITE(op_write)
        ops.flush = _FLUSH(op_flush)
        ops.release = _RELEASE(op_release)
        ops.fsync = _FSYNC(op_fsync)
        ops.readdir = _READDIR(op_readdir)
        ops.access = _ACCESS(op_access)
        ops.create = _CREATE(op_create)
        ops.ftruncate = _FTRUNCATE(op_ftruncate)
        ops.fgetattr = _FGETATTR(op_fgetattr)
        ops.utimens = _UTIMENS(op_utimens)
        return ops

    # -- lifecycle -------------------------------------------------------------
    def mount(self, foreground: bool = False) -> "FuseMount":
        os.makedirs(self.mountpoint, exist_ok=True)
        args = [b"seaweedfs_tpu", self.mountpoint.encode(), b"-f", b"-s"]
        opts = b"big_writes,default_permissions"
        if self.allow_other:
            opts += b",allow_other"
        args += [b"-o", opts]
        argv = (c_char_p * len(args))(*args)

        def run():
            self._rc = self._lib.fuse_main_real(
                len(args), argv, ctypes.byref(self._ops),
                ctypes.sizeof(self._ops), None,
            )
            # libfuse2's teardown restores SIGPIPE to SIG_DFL (it saved the
            # disposition before Python's ignore was visible to it); without
            # re-ignoring, the next EPIPE on any socket KILLS the process
            # instead of raising BrokenPipeError. ctypes because
            # signal.signal() refuses to run outside the main thread.
            try:
                libc = ctypes.CDLL(None, use_errno=True)
                libc.signal.restype = ctypes.c_void_p
                libc.signal.argtypes = [ctypes.c_int, ctypes.c_void_p]
                libc.signal(13, ctypes.c_void_p(1))  # SIGPIPE → SIG_IGN
            except Exception:
                glog.warning("could not re-ignore SIGPIPE after fuse exit")

        if foreground:
            run()
            return self
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        # wait for the kernel mount to appear (or the loop to die)
        deadline = time.time() + 10
        while time.time() < deadline:
            if self._rc is not None and self._rc != 0:
                raise RuntimeError(f"fuse_main failed rc={self._rc}")
            if os.path.ismount(self.mountpoint):
                return self
            time.sleep(0.05)
        raise RuntimeError("fuse mount did not appear within 10s")

    def unmount(self) -> None:
        # plain unmount first; if the mount is busy, fall back to a lazy
        # detach — a mountpoint left behind surfaces later as "Transport
        # endpoint is not connected" when the directory tree is removed
        for cmd in (["fusermount", "-u", self.mountpoint],
                    ["umount", self.mountpoint],
                    ["fusermount", "-uz", self.mountpoint],
                    ["umount", "-l", self.mountpoint]):
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=10)
                if r.returncode == 0:
                    break
            except (OSError, subprocess.TimeoutExpired):
                continue
        # wait for the detach to land before the caller deletes the tree
        for _ in range(50):
            if not os.path.ismount(self.mountpoint):
                break
            time.sleep(0.1)
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._hlock:
            handles, self._handles = dict(self._handles), {}
        for h in handles.values():
            try:
                h.close()
            except Exception:  # sweedlint: ok broad-except best-effort handle drain on unmount; nothing to do with a failed close
                pass
