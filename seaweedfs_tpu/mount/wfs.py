"""WFS: the virtual filesystem over the filer.

Reference: `weed/filesys/wfs.go:55` (the FUSE fs object), `file.go`/
`filehandle.go` (open-file state + dirty pages), `wfs_write.go`
(saveDataAsChunk: assign fid → upload → append chunk), `dir.go`
(directory ops). FUSE wiring is replaced by a plain Python API with the
same operation set; a FUSE binding would be a thin adapter over this.

Write path: writes land in ContinuousIntervals; any continuous run that
reaches chunk_size is eagerly uploaded and committed; flush() uploads the
rest and commits the entry (chunk list) to the filer. Read path: committed
bytes come from the filer (ranged GET), then still-dirty intervals overlay
them — read-your-writes without waiting for a flush.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import operation
from ..filer.client import FilerClient  # noqa: F401 — re-exported for callers
from ..filer.entry import Entry, FileChunk
from .dirty_pages import ContinuousIntervals
from .meta_cache import MetaCache


class WfsError(Exception):
    pass


class WFS:
    def __init__(
        self,
        filer_url: str,
        chunk_size: int = 8 * 1024 * 1024,
        collection: str = "",
        ttl: str = "",
        meta_cache_db: str = ":memory:",
        use_meta_cache: bool = True,
        cipher: Optional[bool] = None,
        read_window: int = 4,
        write_window: int = 4,
    ):
        # multi-address lists route entry commits by ring ownership —
        # direct-to-volume data writes are unaffected, but the COMMIT
        # (create_entry) must land on the path's owning filer
        from ..filer.ring import make_client

        self.client = make_client(filer_url)
        self.chunk_size = chunk_size
        self.collection = collection
        self.ttl = ttl
        # data-plane pipeline depths (util/pipeline.py): bounded windows of
        # concurrent chunk uploads / ranged sub-reads per operation. Peak
        # extra memory per call is window × chunk_size (docs/PERF.md).
        self.read_window = max(1, read_window)
        self.write_window = max(1, write_window)
        if cipher is None:
            # honor the filer's -encryptVolumeData setting the way the
            # reference mount reads GetFilerConfiguration (wfs.go:55) —
            # otherwise every mount write silently bypasses encryption
            try:
                cipher = bool(self.client.status().get("cipher", False))
            except Exception:
                cipher = False
        self.cipher = cipher
        self.meta_cache: Optional[MetaCache] = None
        if use_meta_cache:
            self.meta_cache = MetaCache(filer_url, meta_cache_db).start()

    def close(self) -> None:
        if self.meta_cache:
            self.meta_cache.stop()

    # -- directory ops (filesys/dir.go) --------------------------------------
    def stat(self, path: str) -> Entry:
        e = (
            self.meta_cache.lookup(path)
            if self.meta_cache
            else self._remote_entry(path)
        )
        if e is None:
            raise FileNotFoundError(path)
        return e

    def exists(self, path: str) -> bool:
        try:
            self.stat(path)
            return True
        except FileNotFoundError:
            return False

    def _remote_entry(self, path: str) -> Optional[Entry]:
        d = self.client.get_entry(path)
        return Entry.from_dict(d) if d else None

    def listdir(self, path: str) -> list[Entry]:
        if self.meta_cache:
            return self.meta_cache.list_dir(path)
        return [Entry.from_dict(d) for d in self.client.list(path)]

    def mkdir(self, path: str, mode: int = 0o775) -> None:
        self.client.mkdir(path)
        if self.meta_cache:
            self.meta_cache.invalidate(path)

    def unlink(self, path: str) -> None:
        self.client.delete(path)
        if self.meta_cache:
            self.meta_cache.invalidate(path)

    def rmdir(self, path: str, recursive: bool = False) -> None:
        self.client.delete(path, recursive=recursive)
        if self.meta_cache:
            self.meta_cache.invalidate(path)

    def rename(self, old: str, new: str) -> None:
        self.client.rename(old, new)
        if self.meta_cache:
            self.meta_cache.invalidate(old)
            self.meta_cache.invalidate(new)

    # -- xattr (filesys/xattr.go — entry.Extended carries them) --------------
    XATTR_PREFIX = "xattr-"

    def _commit_meta(self, path: str, entry: Entry) -> None:
        self.client.create_entry(path, entry.to_dict())
        if self.meta_cache:
            self.meta_cache.invalidate(path)

    @property
    def _meta_mu(self):
        """Serializes every read-modify-write entry upsert in this process
        (xattr mutations vs FileHandle chunk commits): two interleaved
        fetch→commit cycles would otherwise revert each other's half —
        a metadata write must never be able to truncate a flushed chunk
        list. Cross-process writers race at the filer like the reference's
        mounts do; in-process is the case the kernel actually produces."""
        mu = getattr(self, "_meta_mu_", None)
        if mu is None:
            mu = self._meta_mu_ = threading.Lock()
        return mu

    def _xattr_gen(self, path: str) -> int:
        return getattr(self, "_ext_gens_", {}).get(path, 0)

    def _bump_xattr_gen(self, path: str) -> None:
        gens = getattr(self, "_ext_gens_", None)
        if gens is None:
            gens = self._ext_gens_ = {}
        gens[path] = gens.get(path, 0) + 1

    def setxattr(self, path: str, name: str, value: bytes,
                 create: bool = False, replace: bool = False) -> None:
        import base64
        import errno

        with self._meta_mu:
            # always the LIVE entry, never a cache: a concurrent flush may
            # have just committed fresh chunks, and upserting a stale chunk
            # list here would truncate the file's new data
            entry = self._remote_entry(path)
            if entry is None:
                raise FileNotFoundError(path)
            ext = dict(entry.extended or {})
            key = self.XATTR_PREFIX + name
            if create and key in ext:
                raise FileExistsError(name)
            if replace and key not in ext:
                raise OSError(errno.ENODATA, name)
            ext[key] = base64.b64encode(value).decode()
            entry.extended = ext
            self._bump_xattr_gen(path)
            self._commit_meta(path, entry)

    def getxattr(self, path: str, name: str) -> bytes:
        import base64
        import errno

        entry = self.stat(path)
        raw = (entry.extended or {}).get(self.XATTR_PREFIX + name)
        if raw is None:
            raise OSError(errno.ENODATA, name)
        return base64.b64decode(raw)

    def listxattr(self, path: str) -> list[str]:
        entry = self.stat(path)
        pre = self.XATTR_PREFIX
        return sorted(
            k[len(pre):] for k in (entry.extended or {}) if k.startswith(pre)
        )

    def removexattr(self, path: str, name: str) -> None:
        import errno

        with self._meta_mu:
            entry = self._remote_entry(path)  # live, not cached (setxattr)
            if entry is None:
                raise FileNotFoundError(path)
            ext = dict(entry.extended or {})
            if ext.pop(self.XATTR_PREFIX + name, None) is None:
                raise OSError(errno.ENODATA, name)
            entry.extended = ext
            self._bump_xattr_gen(path)
            self._commit_meta(path, entry)

    # -- file ops ------------------------------------------------------------
    def open(self, path: str, mode: str = "r") -> "FileHandle":
        """Modes: r, r+, w (truncate/create), a (append/create)."""
        entry: Optional[Entry] = None
        try:
            entry = self.stat(path)
        except FileNotFoundError:
            pass
        if mode in ("r", "r+") and entry is None:
            raise FileNotFoundError(path)
        if entry is not None and entry.is_directory:
            raise IsADirectoryError(path)
        if mode == "w" or entry is None:
            entry = Entry(full_path=path, is_directory=False, mode=0o660)
            entry.chunks = []
            if mode in ("w", "a", "r+"):
                # commit the (possibly truncating) create immediately so
                # concurrent readers see a consistent entry
                self.client.create_entry(path, entry.to_dict())
                if self.meta_cache:
                    self.meta_cache.invalidate(path)
        return FileHandle(self, path, entry, mode)

    # convenience one-shots
    def write_file(self, path: str, data: bytes) -> None:
        with self.open(path, "w") as f:
            f.write(0, data)

    def read_file(self, path: str) -> bytes:
        with self.open(path, "r") as f:
            return f.read(0, f.size())

    # -- chunk upload (wfs_write.go saveDataAsChunk) -------------------------
    def _save_one_chunk(self, piece: bytes, chunk_offset: int) -> FileChunk:
        a = self.client.assign(collection=self.collection, ttl=self.ttl)
        if a.get("error"):
            raise WfsError(f"assign: {a['error']}")
        payload, cipher_key_b64 = piece, ""
        if self.cipher:
            # fresh key per chunk; the volume stores ciphertext and the
            # entry holds the key, same as filer POST (_write_cipher.go)
            import base64

            from ..util import cipher as cipher_mod

            key = cipher_mod.gen_cipher_key()
            payload = cipher_mod.encrypt(piece, key)
            cipher_key_b64 = base64.b64encode(key).decode()
        operation.upload_data(a["url"], a["fid"], payload, jwt=a.get("auth", ""))
        return FileChunk(
            file_id=a["fid"],
            offset=chunk_offset,
            size=len(piece),
            mtime=time.time_ns(),
            cipher_key=cipher_key_b64,
        )

    def save_data_as_chunks(self, data: bytes, base_offset: int) -> list[FileChunk]:
        """Assign+encrypt+upload each chunk_size piece; multi-piece runs go
        through a bounded window of concurrent uploads so chunk k+1 is on
        the wire while chunk k finishes (wfs_write.go saveDataAsChunk under
        concurrentWriters). Chunk order in the returned list is piece
        order; on any failure the window is settled before raising — like
        the reference mount, already-uploaded pieces of an uncommitted run
        are leaked to the volume (vacuum reclaims them), never committed."""
        pieces = [
            (data[pos : pos + self.chunk_size], base_offset + pos)
            for pos in range(0, len(data), self.chunk_size)
        ]
        if len(pieces) <= 1 or self.write_window <= 1:
            return [self._save_one_chunk(p, off) for p, off in pieces]
        from ..util.pipeline import BoundedExecutor

        pipe = BoundedExecutor(self.write_window, name="wfs-write")
        try:
            for piece, off in pieces:
                pipe.submit(self._save_one_chunk, piece, off)
        except BaseException:
            pipe.abort()  # settle in-flight uploads, then surface the error
            raise
        return pipe.drain()  # submit order == piece order


class FileHandle:
    """Open-file state (filesys/filehandle.go): dirty pages + entry view."""

    def __init__(self, wfs: WFS, path: str, entry: Entry, mode: str):
        self.wfs = wfs
        self.path = path
        self.entry = entry
        self.mode = mode
        self.dirty = ContinuousIntervals()
        self._lock = threading.RLock()
        self._closed = False

    # -- context manager -----------------------------------------------------
    def __enter__(self) -> "FileHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def size(self) -> int:
        with self._lock:
            return max(self.entry.file_size(), self.dirty.max_stop())

    def append_offset(self) -> int:
        return self.size()

    # -- write path ----------------------------------------------------------
    def write(self, offset: int, data: bytes) -> int:
        if self.mode == "r":
            raise WfsError("file not open for writing")
        with self._lock:
            if self.mode == "a":
                offset = self.size()
            self.dirty.add_interval(offset, data, time.time_ns())
            # eager flush of full chunk runs (dirty_pages.go)
            while True:
                iv = self.dirty.pop_largest_if_over(self.wfs.chunk_size)
                if iv is None:
                    break
                self._commit_chunks(
                    self.wfs.save_data_as_chunks(iv.data, iv.start)
                )
            return len(data)

    def _commit_chunks(self, new_chunks: list[FileChunk]) -> None:
        with self.wfs._meta_mu:  # vs concurrent xattr read-modify-writes
            self.entry.chunks.extend(new_chunks)
            self.entry.mtime = int(time.time())
            # refresh the extended map before upserting — but only when an
            # xattr mutation actually happened on this path (generation
            # counter), so plain writes don't pay a fetch per flush. An
            # xattr set while this handle was open must not be clobbered
            # by the open-time snapshot; the handle never mutates extended.
            if self.wfs._xattr_gen(self.path):
                remote = self.wfs._remote_entry(self.path)
                if remote is not None:
                    self.entry.extended = dict(remote.extended or {})
            self.wfs._commit_meta(self.path, self.entry)

    def flush(self) -> None:
        with self._lock:
            ivs = self.dirty.pop_all()
            if not ivs:
                return
            chunks: list[FileChunk] = []
            for iv in ivs:
                chunks.extend(self.wfs.save_data_as_chunks(iv.data, iv.start))
            self._commit_chunks(chunks)

    def truncate(self, length: int = 0) -> None:
        """Truncate-to-zero drops all chunks; extension is logical;
        mid-file truncation keeps the [0, length) prefix by re-writing it
        as fresh chunks (correct for cipher'd chunks too, since the read
        path decrypts — chunk-clipping in metadata alone would not be).

        The prefix chunks are UPLOADED BEFORE the entry commit: a failure
        anywhere leaves the old entry (and the data) intact instead of
        committing an emptied chunk list first and losing the file."""
        with self._lock:  # RLock: read() below re-enters; holding it across
            # the whole operation keeps a concurrent acknowledged write from
            # landing between the prefix snapshot and the commit
            new_chunks: list[FileChunk] = []
            if length > 0:
                if length >= self.size():
                    return  # logical extension / no-op
                prefix = self.read(0, length)
                new_chunks = self.wfs.save_data_as_chunks(prefix, 0)
            self.dirty = ContinuousIntervals()
            self.entry.chunks = new_chunks
            self.wfs.client.create_entry(self.path, self.entry.to_dict())
            if self.wfs.meta_cache:
                self.wfs.meta_cache.invalidate(self.path)

    def _read_committed(self, lo: int, hi: int) -> bytes:
        """Fetch committed bytes [lo, hi] inclusive from the filer. Spans
        larger than one chunk split into chunk_size sub-ranges pulled
        through a read_window-deep prefetcher (util/pipeline.py) — each
        worker holds its own pooled keep-alive socket to the filer, so a
        big mount read rides several connections while this thread
        reassembles them in order."""
        from ..util.pipeline import prefetch_iter

        step = self.wfs.chunk_size
        spans = [
            (s, min(s + step - 1, hi)) for s in range(lo, hi + 1, step)
        ]

        def fetch(span):
            s, e = span
            status, data, _ = self.wfs.client.get_object(
                self.path, rng=f"bytes={s}-{e}"
            )
            if status not in (200, 206):
                raise WfsError(f"read {self.path}: HTTP {status}")
            return data

        window = self.wfs.read_window if len(spans) > 1 else 1
        out = bytearray(hi - lo + 1)
        pos = 0
        fetched = prefetch_iter(spans, fetch, window)
        try:
            for _, data in fetched:
                out[pos : pos + len(data)] = data
                pos += len(data)
        finally:
            fetched.close()
        return bytes(out[:pos])

    # -- read path -----------------------------------------------------------
    def read(self, offset: int, size: int) -> bytes:
        with self._lock:
            end = min(offset + size, self.size())
            if end <= offset:
                return b""
            want = end - offset
            base = bytearray(want)
            committed = self.entry.file_size()
            if offset < committed:
                hi = min(end, committed) - 1
                data = self._read_committed(offset, hi)
                base[: len(data)] = data
            # overlay still-dirty bytes (read-your-writes)
            for lo, data in self.dirty.read_data_at(offset, want):
                base[lo - offset : lo - offset + len(data)] = data
            return bytes(base)

    def close(self) -> None:
        if not self._closed:
            self.flush()
            self._closed = True
