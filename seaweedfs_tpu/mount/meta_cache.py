"""Local metadata mirror for the mount layer.

Reference: `weed/filesys/meta_cache/` — a local leveldb mirror of filer
entries, lazily filled on first directory visit and kept fresh by the
filer's `SubscribeMetadata` stream so lookups/readdirs never hit the
network twice. Here: sqlite (the build's embedded KV) + a polling thread
against the filer's `/_meta/events` feed.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Optional

from ..filer.client import FilerClient
from ..util import glog
from ..filer.entry import Entry


def _parent(path: str) -> str:
    if path == "/":
        return "/"
    p = path.rsplit("/", 1)[0]
    return p or "/"


class MetaCache:
    def __init__(self, filer_url: str, db_path: str = ":memory:"):
        self.client = FilerClient(filer_url)
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS entries ("
            " path TEXT PRIMARY KEY, parent TEXT, entry TEXT)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS visited (dir TEXT PRIMARY KEY)"
        )
        self._db.execute("CREATE INDEX IF NOT EXISTS by_parent ON entries(parent)")
        self._lock = threading.Lock()
        self._last_ts_ns = time.time_ns()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- subscription (meta_cache_subscription.go) ---------------------------
    def start(self, poll_seconds: float = 0.5) -> "MetaCache":
        self._thread = threading.Thread(
            target=self._follow, args=(poll_seconds,), daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self._db.close()

    def _follow(self, poll_seconds: float) -> None:
        while not self._stop.wait(poll_seconds):
            try:
                r = self.client.meta_events(since_ns=self._last_ts_ns)
            except Exception as e:
                glog.V(2).info("meta_events poll failed: %s", e)
                continue
            for e in r.get("events", ()):
                self._apply(e)
            self._last_ts_ns = r.get("last_ts_ns", self._last_ts_ns)

    def _apply(self, e: dict) -> None:
        old, new = e.get("old_entry"), e.get("new_entry")
        with self._lock:
            if old and (not new or new["full_path"] != old["full_path"]):
                self._db.execute(
                    "DELETE FROM entries WHERE path = ? OR path LIKE ?",
                    (old["full_path"], old["full_path"] + "/%"),
                )
            if new:
                self._insert(new)
            self._db.commit()

    def _insert(self, entry_dict: dict) -> None:
        path = entry_dict["full_path"]
        self._db.execute(
            "INSERT OR REPLACE INTO entries (path, parent, entry) VALUES (?,?,?)",
            (path, _parent(path), json.dumps(entry_dict)),
        )

    # -- lazy fill (meta_cache_init.go ensureVisited) ------------------------
    def _ensure_visited(self, dir_path: str) -> None:
        with self._lock:
            seen = self._db.execute(
                "SELECT 1 FROM visited WHERE dir = ?", (dir_path,)
            ).fetchone()
        if seen:
            return
        try:
            entries = self.client.list(dir_path)
        except Exception:
            return
        with self._lock:
            for d in entries:
                self._insert(d)
            self._db.execute(
                "INSERT OR REPLACE INTO visited (dir) VALUES (?)", (dir_path,)
            )
            self._db.commit()

    # -- lookups -------------------------------------------------------------
    def lookup(self, path: str) -> Optional[Entry]:
        path = path.rstrip("/") or "/"
        self._ensure_visited(_parent(path))
        with self._lock:
            row = self._db.execute(
                "SELECT entry FROM entries WHERE path = ?", (path,)
            ).fetchone()
            parent_visited = bool(
                self._db.execute(
                    "SELECT 1 FROM visited WHERE dir = ?", (_parent(path),)
                ).fetchone()
            )
        if row:
            return Entry.from_dict(json.loads(row[0]))
        if parent_visited:
            # the cached listing is authoritative: a miss is a real miss —
            # no per-negative-lookup filer round-trip
            return None
        # fall back to the filer (root, or parents whose listing failed)
        d = self.client.get_entry(path)
        if d is None:
            return None
        with self._lock:
            self._insert(d)
            self._db.commit()
        return Entry.from_dict(d)

    def list_dir(self, dir_path: str) -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        self._ensure_visited(dir_path)
        with self._lock:
            rows = self._db.execute(
                "SELECT entry FROM entries WHERE parent = ? ORDER BY path",
                (dir_path,),
            ).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def invalidate(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        with self._lock:
            self._db.execute(
                "DELETE FROM entries WHERE path = ? OR path LIKE ?",
                (path, path + "/%"),
            )
            # drop the listing markers too: the parent's cached listing no
            # longer authoritatively covers this path
            self._db.execute(
                "DELETE FROM visited WHERE dir IN (?, ?)", (path, _parent(path))
            )
            self._db.commit()
