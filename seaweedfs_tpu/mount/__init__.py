"""Mount layer: a filesystem view over the filer, FUSE-less.

Reference: `weed/filesys/` (3,267 LoC) + `weed/command/mount_std.go`. The
reference exposes the filer through the kernel via FUSE; this build exposes
the same machinery as an in-process virtual filesystem (`WFS`) plus a
local-directory synchronizer (`sync`) — the pieces a FUSE binding would
call (lookup/read/write/flush via dirty-page intervals, meta cache kept
fresh by the filer's metadata subscription) are all here and tested
without requiring kernel support in the build environment.
"""

from .dirty_pages import ContinuousIntervals
from .meta_cache import MetaCache
from .wfs import WFS, FileHandle

__all__ = ["WFS", "FileHandle", "ContinuousIntervals", "MetaCache"]
