r"""SQL front-end for the query engine — the piece the reference left
unfinished (`weed/query/sqltypes` has the value model but no parser wired
to `volume_grpc_query.go`; S3 Select clients expect
`SELECT ... FROM s3object WHERE ...`).

Grammar (S3-Select subset):

    SELECT * | field[, field...]         (dotted paths allowed)
    FROM <ident>                          (table name is cosmetic)
    [WHERE <expr>]                        (=, !=, <>, <, <=, >, >=,
                                           LIKE with full %/_ wildcards and
                                           \%/\_ escapes, NOT, AND, OR,
                                           parentheses; string/number
                                           literals; single or double quotes)
    [LIMIT <n>]                           (strict ascii uint)

`parse_sql` compiles to the engine's filter dict ({"and": [...]} etc.), so
evaluation stays in one place (engine._matches).
"""

from __future__ import annotations

import re
from typing import Any, Optional

from ..util.parsers import parse_ascii_uint
from .engine import run_query

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<num>-?\d+(?:\.\d+)?)
      | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
      | (?P<op><=|>=|!=|<>|=|<|>)
      | (?P<punct>[(),*])
      | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {"select", "from", "where", "limit", "and", "or", "not", "like"}


class SqlError(ValueError):
    pass


def _tokenize(sql: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN.match(sql, pos)
        if m is None:
            if sql[pos:].strip() == "":
                break
            raise SqlError(f"bad token at {sql[pos:pos + 20]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group(kind)
        if kind == "word" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        else:
            out.append((kind, text))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i] if self.i < len(self.toks) else ("eof", "")

    def next(self) -> tuple[str, str]:
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind: str, text: Optional[str] = None) -> str:
        k, v = self.next()
        if k != kind or (text is not None and v != text):
            raise SqlError(f"expected {text or kind}, got {v!r}")
        return v

    # -- SELECT ... FROM ... [WHERE ...] [LIMIT n] ---------------------------
    def parse(self) -> tuple[Optional[list], Optional[dict], int]:
        self.expect("kw", "select")
        select = self._projection()
        self.expect("kw", "from")
        self.expect("word")  # table name — cosmetic (s3object)
        where = None
        limit = 0
        if self.peek() == ("kw", "where"):
            self.next()
            where = self._or_expr()
        if self.peek() == ("kw", "limit"):
            self.next()
            text = self.expect("num")
            try:
                # the shared strict wire parser: ascii digits only, so
                # "-5", "2.5", "+3" and "1_0" all fail the same way
                limit = parse_ascii_uint(text)
            except ValueError:
                raise SqlError(
                    f"LIMIT must be a non-negative integer: {text}"
                ) from None
        if self.peek()[0] != "eof":
            raise SqlError(f"trailing input at {self.peek()[1]!r}")
        return select, where, limit

    def _projection(self) -> Optional[list]:
        if self.peek() == ("punct", "*"):
            self.next()
            return None  # engine treats None as all fields
        fields = [self.expect("word")]
        while self.peek() == ("punct", ","):
            self.next()
            fields.append(self.expect("word"))
        return fields

    # -- boolean expression (OR lowest, then AND, NOT, atoms) ----------------
    def _or_expr(self) -> dict:
        terms = [self._and_expr()]
        while self.peek() == ("kw", "or"):
            self.next()
            terms.append(self._and_expr())
        return terms[0] if len(terms) == 1 else {"or": terms}

    def _and_expr(self) -> dict:
        terms = [self._not_expr()]
        while self.peek() == ("kw", "and"):
            self.next()
            terms.append(self._not_expr())
        return terms[0] if len(terms) == 1 else {"and": terms}

    def _not_expr(self) -> dict:
        if self.peek() == ("kw", "not"):
            self.next()
            return {"not": self._not_expr()}
        return self._atom()

    def _atom(self) -> dict:
        if self.peek() == ("punct", "("):
            self.next()
            inner = self._or_expr()
            self.expect("punct", ")")
            return inner
        field = self.expect("word")
        kind, op = self.next()
        if (kind, op) == ("kw", "like"):
            return self._like(field)
        if kind != "op":
            raise SqlError(f"expected comparison after {field!r}, got {op!r}")
        value = self._literal()
        if op == "<>":
            op = "!="
        return {"field": field, "op": op, "value": value}

    def _like(self, field: str) -> dict:
        # take the RAW quoted body: _literal()'s general unescape would
        # collapse \% / \_ into bare wildcards before we can see them
        kind, text = self.next()
        if kind != "str":
            raise SqlError("LIKE needs a string pattern")
        body = text[1:-1]
        atoms: list[tuple] = []  # ("lit", ch) | ("any",) | ("one",)
        i = 0
        while i < len(body):
            c = body[i]
            if c == "\\" and i + 1 < len(body):
                atoms.append(("lit", body[i + 1]))
                i += 2
            elif c == "%":
                atoms.append(("any",))
                i += 1
            elif c == "_":
                atoms.append(("one",))
                i += 1
            else:
                atoms.append(("lit", c))
                i += 1
        lits = "".join(a[1] for a in atoms if a[0] == "lit")
        # the engine's substring ops cover the common S3-Select shapes
        # (and are the ones the scan kernels vectorize): %x% → contains,
        # x% → starts_with, no wildcards → equals; anything else compiles
        # to the general "like" op in canonical escaped form
        if all(a[0] == "lit" for a in atoms):
            return {"field": field, "op": "=", "value": lits}
        if (
            len(atoms) >= 2
            and atoms[0] == ("any",)
            and atoms[-1] == ("any",)
            and all(a[0] == "lit" for a in atoms[1:-1])
        ):
            return {"field": field, "op": "contains", "value": lits}
        if atoms[-1] == ("any",) and all(a[0] == "lit" for a in atoms[:-1]):
            return {"field": field, "op": "starts_with", "value": lits}
        canonical = []
        for a in atoms:
            if a[0] == "any":
                canonical.append("%")
            elif a[0] == "one":
                canonical.append("_")
            elif a[1] in "\\%_":
                canonical.append("\\" + a[1])
            else:
                canonical.append(a[1])
        return {"field": field, "op": "like", "value": "".join(canonical)}

    def _literal(self) -> Any:
        kind, text = self.next()
        if kind == "num":
            return float(text) if "." in text else int(text)
        if kind == "str":
            body = text[1:-1]
            return re.sub(r"\\(.)", r"\1", body)
        raise SqlError(f"expected literal, got {text!r}")


def parse_sql(sql: str) -> tuple[Optional[list], Optional[dict], int]:
    """SQL text → (select, where, limit) in the engine's filter language."""
    return _Parser(_tokenize(sql)).parse()


def run_sql(
    data: bytes, sql: str, input_format: str = "json"
) -> list[dict]:
    select, where, limit = parse_sql(sql)
    return run_query(
        data, input_format=input_format, select=select, where=where,
        limit=limit,
    )
