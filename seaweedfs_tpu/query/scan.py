"""Vectorized S3-Select scan engine: fused filter+project plans over
uint8 byte batches.

``engine.py`` evaluates one document at a time — csv.DictReader, a dict
per row, a Python filter walk per row.  At warm-store scan sizes (ROADMAP
item 4: "S3 Select-class scans as a new workload") that caps out around
3 MB/s/core.  This module compiles the same filter dicts that ``sql.py``
emits into columnar plans that run the EC pattern end to end: stage
bytes → structural index → device batches → fused predicate kernel →
stream matched rows out.

Pipeline per CSV batch (the columnar format; the one the kernels cover):

1. **Structural indexing** (host numpy): newline and delimiter positions
   via dense byte compares + ``flatnonzero``/``searchsorted`` — one
   memory-bound pass that replaces the per-character csv state machine.
2. **Field extraction**: each referenced column becomes a padded
   ``[rows, width]`` uint8 matrix + length vector (a single fancy-index
   gather), the byte-batch layout the kernels consume.
3. **Fused predicate evaluation**: the whole WHERE tree — numeric
   compares, equality, lexicographic ordering, contains / starts_with —
   is one compiled function per plan.  The jax backend jit-compiles it
   (XLA; CPU or TPU per ``JAX_PLATFORMS``), the numpy fallback runs the
   identical expression graph eagerly.  Backend selection mirrors
   ``ec/codec.get_codec``: ``$SWEED_QUERY_BACKEND`` overrides, else jax
   if importable, else numpy.

Byte-identity with ``engine.run_query`` on EVERY input is the contract
(the property test in tests/test_query_scan.py enforces it).  The
kernels therefore compute a *validity* mask alongside the match mask:
any row whose bytes the kernel cannot decide with engine-exact semantics
— quoted CSV fields, ``\\r`` line endings, non-ASCII bytes, numeric
strings outside the simple ``-?\\d+(\\.\\d+)?``/15-digit exact-float
domain, fields longer than the kernel width cap, general LIKE patterns —
is re-evaluated through ``engine._matches`` in a row-at-a-time exact
lane.  JSON input takes the exact lane entirely (vectorized newline
segmentation only); a JSON array document degenerates to the engine,
kept only for protocol completeness.  The kernel/fallback split is
observable: ``sweed_query_*`` counters in ``stats/metrics.py``.

Exactness notes (why the kernel domain is what it is):

- Numeric parse folds ≤15 digits into a float64 mantissa (≤ 2^53, every
  intermediate exact) and divides by an exact power of ten — IEEE
  division rounds correctly, so the kernel float equals ``float(s)``.
  Anything float() might also accept ("+5", "1e3", "nan", "٥", "1_0",
  padded whitespace) is detected by charset and routed exact.
- UTF-8 is order-preserving, so lexicographic *byte* compare equals
  Python's codepoint compare for valid UTF-8; rows with any byte ≥ 0x80
  go exact instead of proving validity (replacement-char folding under
  ``errors="replace"`` can alias distinct byte strings).
- A double quote anywhere makes newlines untrustworthy as record breaks
  (quoted fields may embed them), so scanning switches to the exact csv
  parser from the first line containing one — records fully terminated
  before the first quote are provably unaffected and stay vectorized.
"""

from __future__ import annotations

import csv
import io
import json
import os
from typing import Any, Iterable, Iterator, Optional

import numpy as np

from ..stats.metrics import QUERY_COUNTERS
from ..util import glog
from . import engine as _engine

_MAX_FIELD_W = 512  # fields longer than this go to the exact lane
_ROW_BATCH = 1 << 17  # rows per device batch (bounds device mats ~64 MB)
_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")

# bytes float() could possibly accept somewhere in a number:
# digits, sign/exponent/dot/underscore, inf/nan letters, ascii whitespace
_FLOATISH = np.zeros(256, dtype=bool)
for _b in b"0123456789eE+-._ \t\n\r\x0b\x0cinfatyINFATY":
    _FLOATISH[_b] = True

# exact powers of ten for the ≤15-digit mantissa domain
_TEN_POWS = [10.0 ** k for k in range(16)]


def _pow2(n: int, floor: int = 8) -> int:
    v = floor
    while v < n:
        v <<= 1
    return v


def _want_float(want: Any) -> Optional[float]:
    """float(want) under engine._coerce_pair rules (bools are not
    numbers), or None when the engine would fall back to strings."""
    if isinstance(want, bool):
        return None
    try:
        return float(want)
    except (TypeError, ValueError):
        return None


# --------------------------------------------------------------------------
# kernel primitives — parametrized on xp (numpy | jax.numpy) so the same
# expression graph is the eager fallback AND the jitted kernel body
# --------------------------------------------------------------------------


def _colmask(xp, w, lens):
    return xp.arange(w)[None, :] < lens[:, None]


def _ascii_ok(xp, mat, lens):
    return ~xp.any((mat >= 128) & _colmask(xp, mat.shape[1], lens), axis=1)


def _numeric(xp, mat, lens):
    """→ (vals float64, simple, def_not_float): exact float values where
    the field matches the simple-number domain; a proof that float()
    must fail where the charset says so; everything else is neither and
    belongs to the exact lane."""
    n, w = mat.shape
    cm = _colmask(xp, w, lens)
    isdig = (mat >= 48) & (mat <= 57) & cm
    isdot = (mat == 46) & cm
    neg = (lens > 0) & (mat[:, 0] == 45)
    body0 = xp.where(neg, 1, 0)
    bodymask = cm & (xp.arange(w)[None, :] >= body0[:, None])
    digits = xp.sum(isdig, axis=1)
    dots = xp.sum(isdot, axis=1)
    pattern = xp.all(isdig | isdot | ~bodymask, axis=1)
    first_ix = xp.minimum(body0, w - 1)
    last_ix = xp.maximum(lens - 1, 0)
    first_dig = xp.take_along_axis(isdig, first_ix[:, None], axis=1)[:, 0]
    last_dig = xp.take_along_axis(isdig, last_ix[:, None], axis=1)[:, 0]
    simple = (
        pattern
        & (dots <= 1)
        & (digits >= 1)
        & (digits <= 15)
        & first_dig
        & last_dig
        & (lens > body0)
    )
    # positional digit sum: weight each digit by 10^(digits to its
    # right).  Every term and every partial sum is an integer ≤ 10^15 <
    # 2^53, so the float64 sum is exact in any order.
    right = xp.cumsum(isdig[:, ::-1], axis=1)[:, ::-1] - isdig
    weight = xp.asarray(_TEN_POWS, dtype=xp.float64)[xp.clip(right, 0, 15)]
    digval = xp.where(isdig, (mat & 0x0F).astype(xp.float64), 0.0)
    val = xp.sum(digval * weight, axis=1)
    dotpos = xp.argmax(isdot, axis=1)
    frac = xp.where(dots > 0, lens - 1 - dotpos, 0)
    scale = xp.asarray(_TEN_POWS, dtype=xp.float64)[xp.clip(frac, 0, 15)]
    vals = xp.where(neg, -1.0, 1.0) * val / scale
    floatish = xp.asarray(_FLOATISH)[mat] | ~cm
    def_not_float = (lens == 0) | ~xp.all(floatish, axis=1)
    return vals, simple, def_not_float


def _eq_bytes(xp, mat, lens, nb):
    m = len(nb)
    if m > mat.shape[1]:
        return xp.zeros(mat.shape[0], dtype=bool)
    needle = xp.asarray(np.frombuffer(nb, np.uint8))
    return (lens == m) & xp.all(mat[:, :m] == needle[None, :], axis=1)


def _lex_lt_eq(xp, mat, lens, nb):
    """(field < needle, field == needle) by byte order — equals Python
    str ordering for valid UTF-8 on both sides."""
    m = len(nb)
    n, w = mat.shape
    if m == 0:
        return xp.zeros(n, dtype=bool), lens == 0
    L = min(w, m)
    needle = xp.asarray(np.frombuffer(nb[:L], np.uint8))
    rng = xp.arange(L)[None, :]
    validj = rng < xp.minimum(lens, m)[:, None]
    mm = validj & (mat[:, :L] != needle[None, :])
    has = xp.any(mm, axis=1)
    ix = xp.argmax(mm, axis=1)
    fb = xp.take_along_axis(mat[:, :L], ix[:, None], axis=1)[:, 0]
    lt = xp.where(has, fb < needle[ix], lens < m)
    eq = ~has & (lens == m)
    return lt, eq


def _prefix(xp, mat, lens, nb):
    m = len(nb)
    if m > mat.shape[1]:
        return xp.zeros(mat.shape[0], dtype=bool)
    needle = xp.asarray(np.frombuffer(nb, np.uint8))
    return (lens >= m) & xp.all(mat[:, :m] == needle[None, :], axis=1)


def _substr(xp, mat, lens, nb):
    m = len(nb)
    n, w = mat.shape
    if m > w:
        return xp.zeros(n, dtype=bool)
    needle = xp.asarray(np.frombuffer(nb, np.uint8))
    acc = xp.zeros(n, dtype=bool)
    for o in range(w - m + 1):
        seg = xp.all(mat[:, o : o + m] == needle[None, :], axis=1)
        acc = acc | ((lens >= o + m) & seg)
    return acc


# --------------------------------------------------------------------------
# predicate-tree compiler: filter dict → fn(mats, lens, press) → (match,
# valid).  Traced once per plan by jax.jit (or run eagerly by numpy).
# --------------------------------------------------------------------------


def _build_node(flt, index, kern):
    xp = kern.xp
    if not flt:
        return lambda env, n: (
            xp.ones(n, dtype=bool),
            xp.ones(n, dtype=bool),
        )
    # key precedence mirrors engine._matches exactly
    if "and" in flt:
        kids = [_build_node(f, index, kern) for f in flt["and"]]

        def f_and(env, n):
            ms, vs = zip(*[k(env, n) for k in kids]) if kids else ((), ())
            if not kids:
                return xp.ones(n, dtype=bool), xp.ones(n, dtype=bool)
            all_valid = vs[0]
            definite_false = vs[0] & ~ms[0]
            match = ms[0]
            for mm, vv in zip(ms[1:], vs[1:]):
                all_valid = all_valid & vv
                definite_false = definite_false | (vv & ~mm)
                match = match & mm
            return match & all_valid, all_valid | definite_false

        return f_and
    if "or" in flt:
        kids = [_build_node(f, index, kern) for f in flt["or"]]

        def f_or(env, n):
            if not kids:
                return xp.zeros(n, dtype=bool), xp.ones(n, dtype=bool)
            ms, vs = zip(*[k(env, n) for k in kids])
            all_valid = vs[0]
            definite_true = vs[0] & ms[0]
            for mm, vv in zip(ms[1:], vs[1:]):
                all_valid = all_valid & vv
                definite_true = definite_true | (vv & mm)
            return definite_true, all_valid | definite_true

        return f_or
    if "not" in flt:
        kid = _build_node(flt["not"], index, kern)

        def f_not(env, n):
            mm, vv = kid(env, n)
            return ~mm & vv, vv

        return f_not
    return _build_leaf(flt, index, kern)


def _build_leaf(flt, index, kern):
    xp = kern.xp
    op = flt.get("op", "=")
    field = flt.get("field", "")
    want = flt.get("value")
    fi = index[field]

    if op in ("contains", "starts_with"):
        wb = str(want or "").encode("utf-8")
        if not wb:
            # '' is a substring/prefix of everything, missing fields
            # included (str(got or "") == "")
            return lambda env, n: (
                xp.ones(n, dtype=bool),
                xp.ones(n, dtype=bool),
            )
        search = _substr if op == "contains" else _prefix

        def f_str(env, n):
            mat, lens, present = env[fi]
            # missing rows have lens 0 → no match for a nonempty needle,
            # which is definitive; high-byte rows go exact
            return search(xp, mat, lens, wb), _ascii_ok(xp, mat, lens) | ~present

        return f_str

    if op in _CMP_OPS:
        wf = _want_float(want)
        ws = str(want).encode("utf-8")

        def str_cmp(mat, lens):
            # =/!= only need byte equality — the full lexicographic
            # first-diff kernel (argmax + gather) is for the orderings
            if op in ("=", "!="):
                eq = _eq_bytes(xp, mat, lens, ws)
                return eq if op == "=" else ~eq
            lt, eq = _lex_lt_eq(xp, mat, lens, ws)
            return _pick_cmp(xp, op, lt, eq)

        def f_cmp(env, n):
            mat, lens, present = env[fi]
            ascii_ok = _ascii_ok(xp, mat, lens)
            if wf is None:
                return str_cmp(mat, lens) & present, ascii_ok | ~present
            vals, simple, not_float = _numeric(xp, mat, lens)
            num_match = _num_cmp(xp, op, vals, wf)
            # string-compare fallback rows (engine: float(got) raised,
            # str-vs-str ordering applies) are provably the valid &
            # ~simple & present ones; in the common all-numeric column
            # there are none, so the lex kernel is skipped at runtime
            need_str = not_float & ascii_ok & present
            str_match = kern.cond(
                xp.any(need_str),
                lambda: str_cmp(mat, lens),
                lambda: xp.zeros(mat.shape[0], dtype=bool),
            )
            match = xp.where(simple, num_match, str_match)
            valid = simple | (not_float & ascii_ok)
            # engine: got is None → False before any coercion
            return match & present, valid | ~present

        return f_cmp

    # "like" and unknown ops: every PRESENT row goes to the exact lane
    # (engine raises ValueError there for unknown ops, exactly as
    # run_query would); missing rows are a definitive False — the
    # engine's `got is None` check fires before op dispatch.
    def f_exact(env, n):
        _, _, present = env[fi]
        return xp.zeros(n, dtype=bool), ~present

    return f_exact


def _pick_cmp(xp, op, lt, eq):
    if op == "=":
        return eq
    if op == "!=":
        return ~eq
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return ~(lt | eq)
    return ~lt  # >=


def _num_cmp(xp, op, vals, wf):
    if op == "=":
        return vals == wf
    if op == "!=":
        return vals != wf
    if op == "<":
        return vals < wf
    if op == "<=":
        return vals <= wf
    if op == ">":
        return vals > wf
    return vals >= wf


def _leaf_fields(flt, out):
    if not flt:
        return out
    if "and" in flt:
        for f in flt["and"]:
            _leaf_fields(f, out)
    elif "or" in flt:
        for f in flt["or"]:
            _leaf_fields(f, out)
    elif "not" in flt:
        _leaf_fields(flt["not"], out)
    else:
        out.append(flt.get("field", ""))
    return out


# --------------------------------------------------------------------------
# backends — selected like the EC path (ec/codec.get_codec)
# --------------------------------------------------------------------------


class NumpyKernels:
    """Eager numpy evaluation of the same expression graph the jax
    backend traces — the fallback for jax-less hosts and the bench's
    mid-tier comparison point."""

    name = "numpy"
    pads_batches = False  # eager: no retrace cost, no padding needed

    def __init__(self):
        self.xp = np

    def compile(self, fn, static_argnums=()):
        return fn

    def cond(self, pred, tfn, ffn):
        return tfn() if pred else ffn()

    def stage(self, buf: np.ndarray) -> np.ndarray:
        return buf

    def to_host(self, x):
        return np.asarray(x)


class JaxKernels:
    """jit-compiled fused predicate kernels (XLA; CPU or TPU per
    JAX_PLATFORMS).  x64 is required: the numeric-compare kernel's
    exactness proof lives in float64 mantissa arithmetic."""

    pads_batches = True  # pow2 row buckets bound the jit retrace count

    def __init__(self):
        import jax  # noqa: F401 — ImportError → numpy fallback upstream

        jax.config.update("jax_enable_x64", True)
        import jax.numpy as jnp

        self._jax = jax
        self.xp = jnp
        self.name = f"jax-{jax.default_backend()}"

    def compile(self, fn, static_argnums=()):
        return self._jax.jit(fn, static_argnums=static_argnums)

    def cond(self, pred, tfn, ffn):
        """Runtime branch inside a traced kernel — lets a plan skip the
        lexicographic fallback compare when no row in the batch needs it
        (the common all-numeric-column case)."""
        return self._jax.lax.cond(pred, tfn, ffn)

    def stage(self, buf: np.ndarray):
        """Move a segment's byte buffer to the device once, pow2-padded
        so batch calls against it hit a bounded set of traced shapes."""
        cap = _pow2(len(buf), 1 << 16)
        if cap != len(buf):
            grown = np.zeros(cap, dtype=np.uint8)
            grown[: len(buf)] = buf
            buf = grown
        return self._jax.device_put(buf)

    def to_host(self, x):
        return np.asarray(x)


_BACKENDS = {
    "numpy": NumpyKernels,
    "jax": JaxKernels,
    "cpu": JaxKernels,
    "tpu": JaxKernels,
}


def get_kernels(backend: Optional[str] = None):
    """SWEED_QUERY_BACKEND=numpy|jax(|cpu|tpu) overrides; default is jax
    when importable, numpy otherwise — the ec/codec.get_codec shape."""
    if backend is None:
        backend = os.environ.get("SWEED_QUERY_BACKEND", "")
    backend = (backend or "").strip().lower()
    if backend and backend != "auto":
        try:
            cls = _BACKENDS[backend]
        except KeyError:
            raise ValueError(
                f"unknown query backend {backend!r} "
                f"(want one of {sorted(_BACKENDS)})"
            ) from None
        try:
            return cls()
        except ImportError:
            glog.warning("query backend %s unavailable; using numpy", backend)
            return NumpyKernels()
    try:
        return JaxKernels()
    except ImportError:
        return NumpyKernels()


# --------------------------------------------------------------------------
# the plan
# --------------------------------------------------------------------------


class ScanPlan:
    """One compiled filter+project plan.  Thread-compatible, not
    thread-safe: run one scan at a time per plan (each daemon request
    compiles its own — compilation is cheap next to the scan)."""

    def __init__(
        self,
        select: Optional[list] = None,
        where: Optional[dict] = None,
        limit: int = 0,
        input_format: str = "json",
        backend: Optional[str] = None,
    ):
        self.select = select
        self.where = where
        self.limit = int(limit or 0)
        self.input_format = input_format
        self.kernels = get_kernels(backend)
        self.stats = {"rows_scanned": 0, "rows_kernel": 0,
                      "rows_fallback": 0, "bytes_scanned": 0}
        self._fields = sorted(set(_leaf_fields(where, [])))
        self._index = {f: i for i, f in enumerate(self._fields)}
        # select-list columns need spans for projection but no kernel mats
        self._proj_fields = (
            list(dict.fromkeys(select))
            if select and select != ["*"] else None
        )
        if self._fields and input_format == "csv":
            xp = self.kernels.xp
            node = _build_node(where, self._index, self.kernels)

            def tree(pad, fss, lens, press, widths):
                # field gather fused into the kernel: on jax the byte
                # matrices never materialize host-side (widths static)
                env = [
                    (pad[fs[:, None] + xp.arange(w, dtype=fs.dtype)[None, :]],
                     fl, pr)
                    for fs, fl, pr, w in zip(fss, lens, press, widths)
                ]
                return node(env, lens[0].shape[0])

            self._eval = self.kernels.compile(tree, static_argnums=(4,))
        else:
            self._eval = None

    # -- public API ---------------------------------------------------------

    def execute(self, data: bytes) -> list[dict]:
        """Byte-identical to engine.run_query(data, ...) for this plan."""
        out: list[dict] = []
        for batch in self.scan_iter(iter((data,))):
            out.extend(batch)
        return out

    def scan_iter(self, chunks: Iterable[bytes]) -> Iterator[list[dict]]:
        """Streaming core: consume byte chunks (any split points), yield
        batches of matched+projected rows.  Stops consuming as soon as
        the LIMIT is reached, so a prefetching producer gets closed
        early instead of staging the whole object."""
        self.stats = {"rows_scanned": 0, "rows_kernel": 0,
                      "rows_fallback": 0, "bytes_scanned": 0}
        QUERY_COUNTERS["scans"].inc(backend=self.kernels.name)
        if self.input_format == "csv":
            yield from self._scan_csv(chunks)
        else:
            yield from self._scan_json(chunks)

    # -- CSV ----------------------------------------------------------------

    def _scan_csv(self, chunks) -> Iterator[list[dict]]:
        emitted = 0
        header: Optional[list] = None
        carry = b""
        exact_tail: list[bytes] = []  # doc-mode remainder (quotes / \r)
        done = False

        def room() -> int:
            return (self.limit - emitted) if self.limit else -1

        for chunk in chunks:
            self._count_bytes(len(chunk))
            if exact_tail:
                exact_tail.append(chunk)
                continue
            data = carry + chunk if carry else chunk
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            seg, carry = data[: cut + 1], data[cut + 1 :]
            header, rows, tail = self._csv_segment(seg, header, room())
            if rows:
                emitted += len(rows)
                yield rows
                if self.limit and emitted >= self.limit:
                    # break now, not at the top of the next iteration:
                    # the for-loop would pull (and discard) one more chunk
                    # from the source, skewing upstream byte counters
                    done = True
                    break
            if tail is not None:
                exact_tail.append(tail)
                if carry:
                    # keep byte order: the unterminated carry precedes
                    # any chunks appended on later iterations
                    exact_tail.append(carry)
                    carry = b""
        if done:
            return
        if exact_tail:
            exact_tail.append(carry)
            rows = self._csv_exact(b"".join(exact_tail), header, room())
            if rows:
                yield rows
            return
        if carry:
            # final unterminated line
            header, rows, tail = self._csv_segment(carry, header, room())
            if tail is not None:
                rows = rows + self._csv_exact(tail, header, room() - len(rows)
                                              if self.limit else -1)
            if rows:
                yield rows

    def _count_bytes(self, n: int) -> None:
        self.stats["bytes_scanned"] += n
        QUERY_COUNTERS["bytes"].inc(n)

    def _count_rows(self, kernel: int, fallback: int) -> None:
        self.stats["rows_scanned"] += kernel + fallback
        self.stats["rows_kernel"] += kernel
        self.stats["rows_fallback"] += fallback
        if kernel:
            QUERY_COUNTERS["rows"].inc(kernel)
            QUERY_COUNTERS["kernel"].inc(kernel)
        if fallback:
            QUERY_COUNTERS["rows"].inc(fallback)
            QUERY_COUNTERS["fallback"].inc(fallback)

    def _csv_segment(self, seg, header, room):
        """Vectorized scan of one run of complete lines.  Returns
        (header, matched_rows, exact_tail_bytes_or_None); the tail is
        everything from the first line containing a quote or CR onward —
        bytes the newline index cannot be trusted for.  Byte accounting
        happens once per incoming chunk in _scan_csv, not here."""
        tail = None
        q1, q2 = seg.find(b'"'), seg.find(b"\r")
        q = min(x for x in (q1, q2) if x >= 0) if max(q1, q2) >= 0 else -1
        if q >= 0:
            ls = seg.rfind(b"\n", 0, q) + 1
            seg, tail = seg[:ls], seg[ls:]
        consumed = 0
        if header is None and seg:
            nl = seg.find(b"\n")
            first = seg if nl < 0 else seg[:nl]
            consumed = len(seg) if nl < 0 else nl + 1
            got = list(csv.reader([first.decode("utf-8", errors="replace")]))
            header = got[0] if got else []
        if header is None:
            # no complete line yet and a quote in the header region
            return header, [], tail
        body = seg[consumed:]
        rows: list[dict] = []
        if body:
            arr = np.frombuffer(body, np.uint8)
            if self._eval is not None:
                # pad once (pow2 for jit backends) so field gathers need
                # no per-batch clamping: any in-bounds span plus the
                # width overhang lands in the pad.  Only the overhang
                # window needs zeroing — every kernel read past a
                # field's length is masked by lens/colmask
                cap = len(arr) + _MAX_FIELD_W + 8
                if self.kernels.pads_batches:
                    cap = _pow2(cap, 1 << 16)
                pad = np.empty(cap, dtype=np.uint8)
                pad[: len(arr)] = arr
                pad[len(arr): len(arr) + _MAX_FIELD_W + 8] = 0
                staged = self.kernels.stage(pad)
            else:
                staged = None
            idt = np.int32 if len(arr) < 2**31 - 2 * _MAX_FIELD_W else np.int64
            nls = np.flatnonzero(arr == 10).astype(idt)
            starts = np.empty(len(nls) + 1, dtype=idt)
            starts[0] = 0
            np.add(nls, 1, out=starts[1:])
            ends = np.empty(len(nls) + 1, dtype=idt)
            ends[: len(nls)] = nls
            ends[-1] = len(arr)
            keep = ends > starts  # DictReader skips blank rows
            allkeep = bool(keep.all())
            if self._eval is not None:
                # sentinel commas (== len(arr), pointing at the pad) make
                # out-of-row column indices safe without clamping
                nsent = len(header) + 2
                real = np.flatnonzero(arr == 44)
                commas = np.empty(len(real) + nsent, dtype=idt)
                commas[: len(real)] = real
                commas[len(real):] = len(arr)
                # first-comma index per line, once per segment: the gap
                # between a line's end and the next line's start is just
                # the newline byte, so ci1 is ci0 shifted
                ci0 = np.searchsorted(
                    commas[: len(real)], starts).astype(idt)
                ci1 = np.empty_like(ci0)
                ci1[:-1] = ci0[1:]
                ci1[-1] = len(real)
                if not allkeep:
                    ci0, ci1 = ci0[keep], ci1[keep]
            else:
                commas, ci0, ci1 = None, None, None
            if not allkeep:
                starts, ends = starts[keep], ends[keep]
            for lo in range(0, len(starts), _ROW_BATCH):
                if room >= 0 and len(rows) >= room:
                    break
                hi = min(lo + _ROW_BATCH, len(starts))
                rows.extend(
                    self._csv_batch(
                        body, staged, starts[lo:hi], ends[lo:hi], commas,
                        None if ci0 is None else ci0[lo:hi],
                        None if ci1 is None else ci1[lo:hi], header,
                        -1 if room < 0 else room - len(rows),
                    )
                )
        return header, rows, tail

    def _csv_batch(self, body, staged, starts, ends, commas, ci0, ci1,
                   header, room):
        n = len(starts)
        exact = np.zeros(n, dtype=bool)
        if self._eval is not None:
            ncols = (ci1 - ci0) + 1
            # pow2 row bucket for jit backends: every batch shape recurs,
            # so the tree compiles once per (rows, widths) bucket instead
            # of once per ragged tail
            nb = _pow2(n, 1024) if self.kernels.pads_batches else n
            fss, lens_l, press, widths = [], [], [], []
            for f in self._fields:
                # (start, len, present) of the referenced column under
                # last-dup header semantics (DictReader dict(zip(...)) +
                # restval fill).  Non-present rows keep garbage-but-in-
                # pad starts and length 0; kernels mask by both.
                if "." in f or f not in header:
                    fs = np.zeros(n, dtype=starts.dtype)
                    fl = fs
                    present = np.zeros(n, dtype=bool)
                else:
                    c = len(header) - 1 - header[::-1].index(f)
                    present = c < ncols
                    fs = starts if c == 0 else commas[ci0 + c - 1] + 1
                    fe = np.where(c < ncols - 1, commas[ci0 + c], ends)
                    fl = np.where(present, fe - fs, 0)
                    too_long = fl > _MAX_FIELD_W
                    if too_long.any():
                        exact |= too_long & present
                        fl = np.where(too_long, 0, fl)
                        present = present & ~too_long
                if nb != n:
                    fs = np.concatenate(
                        (fs, np.zeros(nb - n, dtype=fs.dtype)))
                    fl = np.concatenate(
                        (fl, np.zeros(nb - n, dtype=fl.dtype)))
                    present = np.concatenate(
                        (present, np.zeros(nb - n, dtype=bool)))
                fss.append(fs)
                lens_l.append(np.asarray(fl, dtype=np.int32))
                press.append(present)
                widths.append(
                    _pow2(min(int(fl.max()) if n else 1, _MAX_FIELD_W) or 1)
                )
            match, valid = self._eval(staged, fss, lens_l, press,
                                      tuple(widths))
            match = np.asarray(self.kernels.to_host(match), dtype=bool)[:n]
            valid = np.asarray(self.kernels.to_host(valid), dtype=bool)[:n]
            sel = match & valid & ~exact
            exact |= ~valid
        elif self.where:
            # filter references no fields at all ({"and": []} …): its
            # value is document-independent
            sel = np.full(n, _engine._matches({}, self.where))
        else:
            sel = np.ones(n, dtype=bool)

        need_exact = np.flatnonzero(exact)
        if len(need_exact):
            sel = sel.copy()
            for i in need_exact:
                doc = self._csv_doc(body, int(starts[i]), int(ends[i]), header)
                sel[i] = _engine._matches(doc, self.where)
        self._count_rows(n - len(need_exact), len(need_exact))

        proj_cols = None
        if self._proj_fields is not None:
            proj_cols = [
                (f,
                 len(header) - 1 - header[::-1].index(f)
                 if "." not in f and f in header else -1)
                for f in self._proj_fields
            ]
        out = []
        for i in np.flatnonzero(sel):
            if room >= 0 and len(out) >= room:
                break
            if proj_cols is not None:
                fields = body[int(starts[i]): int(ends[i])].decode(
                    "utf-8", errors="replace").split(",")
                # value = col if the row reaches the column's LAST dup
                # index, else None — exactly DictReader's zip + restval
                # overwrite behavior
                out.append({
                    f: fields[c] if 0 <= c < len(fields) else None
                    for f, c in proj_cols
                })
            else:
                out.append(
                    self._csv_doc(body, int(starts[i]), int(ends[i]), header)
                )
        return out

    @staticmethod
    def _csv_doc(body, s, e, header):
        """Replicate DictReader's dict building for one quote-free line
        (restkey None for long rows, restval None fill for short — and
        the fill OVERWRITES duplicated trailing names, same as the
        stdlib)."""
        fields = body[s:e].decode("utf-8", errors="replace").split(",")
        d = dict(zip(header, fields))
        lf, lr = len(header), len(fields)
        if lf < lr:
            d[None] = fields[lf:]
        elif lf > lr:
            for key in header[lr:]:
                d[key] = None
        return d

    def _csv_exact(self, data, header, room) -> list[dict]:
        """Exact lane for quoted / CR-bearing regions: the stdlib csv
        parser resumed at a record boundary with the header captured by
        the vectorized prefix."""
        text = data.decode("utf-8", errors="replace")
        if header is None:
            reader = csv.DictReader(io.StringIO(text))
        else:
            reader = csv.DictReader(io.StringIO(text), fieldnames=header)
        out = []
        nrows = 0
        for doc in reader:
            nrows += 1
            if _engine._matches(doc, self.where):
                out.append(_engine._project(doc, self.select))
                if room >= 0 and len(out) >= room:
                    break
        self._count_rows(0, nrows)
        return out

    # -- JSON ---------------------------------------------------------------

    def _scan_json(self, chunks) -> Iterator[list[dict]]:
        """JSON-lines stream through the exact lane (structural newline
        segmentation is the only vectorizable part); a JSON array
        document buffers and degenerates to the engine."""
        emitted = 0
        carry = b""
        mode = None  # None → undecided, "lines", "doc"
        doc_buf: list[bytes] = []
        for chunk in chunks:
            self._count_bytes(len(chunk))
            if mode == "doc":
                doc_buf.append(chunk)
                continue
            carry += chunk
            if mode is None:
                probe = carry.decode("utf-8", errors="replace").lstrip()
                if not probe:
                    continue  # pure whitespace so far; keep buffering
                mode = "doc" if probe.startswith("[") else "lines"
                if mode == "doc":
                    doc_buf.append(carry)
                    carry = b""
                    continue
            cut = carry.rfind(b"\n")
            if cut < 0:
                continue
            seg, carry = carry[: cut + 1], carry[cut + 1 :]
            rows, emitted = self._json_lines(seg, emitted)
            if rows:
                yield rows
            if self.limit and emitted >= self.limit:
                return
        if mode == "doc":
            data = b"".join(doc_buf)
            docs = list(_engine._iter_docs(data, "json"))
            self._count_rows(0, len(docs))
            out = []
            for doc in docs:
                if _engine._matches(doc, self.where):
                    out.append(_engine._project(doc, self.select))
                    if self.limit and len(out) >= self.limit:
                        break
            if out:
                yield out
        elif carry:
            rows, emitted = self._json_lines(carry, emitted)
            if rows:
                yield rows

    def _json_lines(self, seg: bytes, emitted: int):
        out = []
        nrows = 0
        for line in seg.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            nrows += 1
            if _engine._matches(doc, self.where):
                out.append(_engine._project(doc, self.select))
                emitted += 1
                if self.limit and emitted >= self.limit:
                    break
        self._count_rows(0, nrows)
        return out, emitted


def compile_plan(
    select: Optional[list] = None,
    where: Optional[dict] = None,
    limit: int = 0,
    input_format: str = "json",
    backend: Optional[str] = None,
) -> ScanPlan:
    return ScanPlan(select, where, limit, input_format, backend)


def run_scan(
    data: bytes,
    input_format: str = "json",
    select: Optional[list] = None,
    where: Optional[dict] = None,
    limit: int = 0,
    backend: Optional[str] = None,
) -> list[dict]:
    """Drop-in vectorized twin of engine.run_query."""
    return compile_plan(select, where, limit, input_format, backend).execute(
        data
    )
