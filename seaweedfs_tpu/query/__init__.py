"""S3-Select-ish content query engine (reference `weed/server/
volume_grpc_query.go:12` + `weed/query/json`): server-side filtering and
projection of CSV / JSON-lines object content."""

from ..util.parsers import tolerant_uint
from .engine import run_query  # noqa: F401
from .sql import parse_sql, run_sql  # noqa: F401


def execute_request(data: bytes, req: dict) -> tuple[int, dict]:
    """Run one query request dict against raw bytes → (status, payload).

    The shared execution core behind the filer's /_query and the volume
    server's data-local /_query (volume_grpc_query.go runs next to the
    needle bytes; this is that execution, callable from either daemon)."""
    if req.get("sql"):
        from .sql import SqlError, run_sql

        try:
            rows = run_sql(
                data, req["sql"], input_format=req.get("input", "json")
            )
        except SqlError as e:
            return 400, {"error": f"bad sql: {e}"}
    else:
        rows = run_query(
            data,
            input_format=req.get("input", "json"),
            select=req.get("select"),
            where=req.get("where"),
            # strict ascii-digit parse with negative/garbage clamped to
            # the unlimited default — '+5', ' 5 ' and '-5' must not pick
            # rows by accident (and ?limit=-5 would slice from the tail)
            limit=tolerant_uint(req.get("limit", 0), 0),
        )
    return 200, {"rows": rows, "count": len(rows)}
