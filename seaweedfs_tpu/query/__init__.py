"""S3-Select-ish content query engine (reference `weed/server/
volume_grpc_query.go:12` + `weed/query/json`): server-side filtering and
projection of CSV / JSON-lines object content."""

from .engine import run_query  # noqa: F401
from .sql import parse_sql, run_sql  # noqa: F401
