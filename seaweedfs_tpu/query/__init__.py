"""S3-Select-ish content query engine (reference `weed/server/
volume_grpc_query.go:12` + `weed/query/json`): server-side filtering and
projection of CSV / JSON-lines object content.

Three layers:

* ``engine``  — row-at-a-time evaluator; the semantic oracle.
* ``scan``    — vectorized columnar kernels (jit-compiled JAX with a
  numpy fallback) compiling the same filter dicts into fused
  filter+project plans, byte-identical to the engine on every input.
* ``select``  — the S3 SelectObjectContent wire protocol (request XML +
  AWS event-stream framing) on top of ``scan``.
"""

from ..util.parsers import tolerant_uint
from .engine import run_query  # noqa: F401
from .scan import ScanPlan, compile_plan, get_kernels, run_scan  # noqa: F401
from .sql import parse_sql, run_sql  # noqa: F401


def scan_request(chunks, req: dict) -> tuple[int, dict]:
    """Run one query request dict against a byte-chunk stream →
    (status, payload).

    The streaming execution core behind the filer's /_query: chunks come
    straight from the filer's prefetching read path, so a multi-chunk
    object flows through the vectorized plan without ever materializing
    whole.  Output is byte-identical to engine.run_query on the
    concatenated stream (the scan plans are differential-tested for
    exactly that)."""
    from .sql import SqlError

    if req.get("sql"):
        try:
            select, where, limit = parse_sql(req["sql"])
        except SqlError as e:
            return 400, {"error": f"bad sql: {e}"}
    else:
        select = req.get("select")
        where = req.get("where")
        # strict ascii-digit parse with negative/garbage clamped to
        # the unlimited default — '+5', ' 5 ' and '-5' must not pick
        # rows by accident (and ?limit=-5 would slice from the tail)
        limit = tolerant_uint(req.get("limit", 0), 0)
    plan = ScanPlan(
        select=select, where=where, limit=limit,
        input_format=req.get("input", "json"),
    )
    rows = [r for batch in plan.scan_iter(chunks) for r in batch]
    return 200, {"rows": rows, "count": len(rows)}


def execute_request(data: bytes, req: dict) -> tuple[int, dict]:
    """Buffered variant of scan_request — the volume server's data-local
    /_query hands in the whole needle's bytes."""
    return scan_request(iter((data,)), req)
