"""Query evaluation over CSV / JSON-lines blobs.

Filter spec: {"field": "a.b", "op": "=", "value": x} — ops =, !=, <, <=, >,
>=, contains, starts_with. Projection: list of (dotted) field names or ["*"].
Mirrors the semantics of `volume_grpc_query.go` (gjson path lookup + the
same operator set) without the SQL front-end.
"""

from __future__ import annotations

import csv
import io
import json
from typing import Any, Optional


def _get_path(doc: dict, path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list) and part.isdigit():
            idx = int(part)
            cur = cur[idx] if idx < len(cur) else None
        else:
            return None
    return cur


def _coerce_pair(a: Any, b: Any) -> tuple[Any, Any]:
    """Compare numerically when both sides look numeric."""
    try:
        return float(a), float(b)
    except (TypeError, ValueError):
        return str(a), str(b)


def _matches(doc: dict, flt: Optional[dict]) -> bool:
    if not flt:
        return True
    # compound filters from the SQL front-end: {"and": [...]} / {"or": [...]}
    # / {"not": {...}} nest arbitrarily around leaf comparisons
    if "and" in flt:
        return all(_matches(doc, f) for f in flt["and"])
    if "or" in flt:
        return any(_matches(doc, f) for f in flt["or"])
    if "not" in flt:
        return not _matches(doc, flt["not"])
    got = _get_path(doc, flt.get("field", ""))
    op = flt.get("op", "=")
    want = flt.get("value")
    if op in ("contains", "starts_with"):
        s, w = str(got or ""), str(want or "")
        return s.find(w) >= 0 if op == "contains" else s.startswith(w)
    if got is None:
        return False
    a, b = _coerce_pair(got, want)
    if op == "=":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ValueError(f"unknown op {op!r}")


def _project(doc: dict, select: Optional[list[str]]) -> dict:
    if not select or select == ["*"]:
        return doc
    return {f: _get_path(doc, f) for f in select}


def _iter_docs(data: bytes, input_format: str):
    if input_format == "csv":
        text = data.decode("utf-8", errors="replace")
        yield from csv.DictReader(io.StringIO(text))
        return
    # json: one object per line, or a single array/object
    text = data.decode("utf-8", errors="replace").strip()
    if text.startswith("["):
        for doc in json.loads(text):
            yield doc
        return
    for line in text.splitlines():
        line = line.strip()
        if line:
            yield json.loads(line)


def run_query(
    data: bytes,
    input_format: str = "json",
    select: Optional[list[str]] = None,
    where: Optional[dict] = None,
    limit: int = 0,
) -> list[dict]:
    out = []
    for doc in _iter_docs(data, input_format):
        if _matches(doc, where):
            out.append(_project(doc, select))
            if limit and len(out) >= limit:
                break
    return out
