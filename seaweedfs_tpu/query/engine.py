"""Query evaluation over CSV / JSON-lines blobs.

Filter spec: {"field": "a.b", "op": "=", "value": x} — ops =, !=, <, <=, >,
>=, contains, starts_with, like. Projection: list of (dotted) field names or
["*"]. Mirrors the semantics of `volume_grpc_query.go` (gjson path lookup +
the same operator set) without the SQL front-end.

The ``like`` value is a canonical SQL LIKE pattern: ``%`` matches any run
(including across newlines), ``_`` matches one character, and ``\\`` escapes
the next character; the whole value must match. The SQL front-end emits
this op only for patterns its contains/starts_with/equality translations
cannot express.

This row-at-a-time evaluator is the semantic oracle for the vectorized
plans in ``scan.py`` — every behavior here, including the coercion corner
cases, is mirrored there kernel-side or routed to this module's functions
through the per-row exact lane.
"""

from __future__ import annotations

import csv
import io
import json
import re
from typing import Any, Optional


def _get_path(doc: dict, path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list):
            # ascii-digits only: "-1" must not slice from the tail, and
            # unicode digits ("١٢" passes str.isdigit) must not index
            if not (part.isascii() and part.isdigit()):
                return None
            idx = int(part)
            cur = cur[idx] if 0 <= idx < len(cur) else None
        else:
            return None
    return cur


def _coerce_pair(a: Any, b: Any) -> tuple[Any, Any]:
    """Compare numerically when both sides look numeric.

    JSON booleans are NOT numbers here: float(True) == 1.0 made
    ``WHERE flag = 1`` match ``{"flag": true}``; a bool on either side
    forces bool/string comparison instead."""
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return a, b
        return str(a), str(b)
    try:
        return float(a), float(b)
    except (TypeError, ValueError):
        return str(a), str(b)


_LIKE_CACHE: dict[str, "re.Pattern[str]"] = {}


def _like_regex(pattern: str) -> "re.Pattern[str]":
    """Canonical LIKE pattern → anchored regex (cached; patterns come from
    parsed SQL, so the cache is bounded by distinct queries seen)."""
    rx = _LIKE_CACHE.get(pattern)
    if rx is not None:
        return rx
    out, i = [], 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
        elif c == "%":
            out.append(".*")
            i += 1
        elif c == "_":
            out.append(".")
            i += 1
        else:
            out.append(re.escape(c))
            i += 1
    rx = re.compile("(?s)" + "".join(out) + r"\Z")
    if len(_LIKE_CACHE) < 1024:
        _LIKE_CACHE[pattern] = rx
    return rx


def _matches(doc: dict, flt: Optional[dict]) -> bool:
    if not flt:
        return True
    # compound filters from the SQL front-end: {"and": [...]} / {"or": [...]}
    # / {"not": {...}} nest arbitrarily around leaf comparisons
    if "and" in flt:
        return all(_matches(doc, f) for f in flt["and"])
    if "or" in flt:
        return any(_matches(doc, f) for f in flt["or"])
    if "not" in flt:
        return not _matches(doc, flt["not"])
    got = _get_path(doc, flt.get("field", ""))
    op = flt.get("op", "=")
    want = flt.get("value")
    if op in ("contains", "starts_with"):
        s, w = str(got or ""), str(want or "")
        return s.find(w) >= 0 if op == "contains" else s.startswith(w)
    if got is None:
        return False
    if op == "like":
        return _like_regex(str(want)).match(str(got)) is not None
    a, b = _coerce_pair(got, want)
    if op == "=":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ValueError(f"unknown op {op!r}")


def _project(doc: dict, select: Optional[list[str]]) -> dict:
    if not select or select == ["*"]:
        return doc
    return {f: _get_path(doc, f) for f in select}


def _iter_docs(data: bytes, input_format: str):
    if input_format == "csv":
        text = data.decode("utf-8", errors="replace")
        yield from csv.DictReader(io.StringIO(text))
        return
    # json: one object per line, or a single array/object
    text = data.decode("utf-8", errors="replace").strip()
    if text.startswith("["):
        for doc in json.loads(text):
            yield doc
        return
    for line in text.splitlines():
        line = line.strip()
        if line:
            yield json.loads(line)


def run_query(
    data: bytes,
    input_format: str = "json",
    select: Optional[list[str]] = None,
    where: Optional[dict] = None,
    limit: int = 0,
) -> list[dict]:
    out = []
    for doc in _iter_docs(data, input_format):
        if _matches(doc, where):
            out.append(_project(doc, select))
            if limit and len(out) >= limit:
                break
    return out
