"""S3 SelectObjectContent protocol: request XML + AWS event-stream framing.

The reference never finished its query path (`weed/query/sqltypes` has a
value model with no parser; `volume_grpc_query.go` is a stub), so S3
Select clients have nothing to talk to.  This module implements the wire
protocol ends of that feature:

* ``parse_select_request`` — the POST body XML (Expression +
  ExpressionType, InputSerialization for CSV / JSON-lines including
  CompressionType GZIP, OutputSerialization CSV / JSON, RequestProgress),
  validated into a :class:`SelectRequest` with AWS error codes
  (``MalformedXML``, ``InvalidExpressionType``, ``UnsupportedSqlStructure``,
  ``InvalidCompressionFormat``, ``InvalidRequest``).
* the AWS event-stream binary framing (`AWS SigV4 streaming / S3 Select
  response encoding <https://docs.aws.amazon.com/AmazonS3/latest/API/
  RESTSelectObjectAppendix.html>`_): each message is

      prelude  = total_length(u32 BE) . headers_length(u32 BE)
      message  = prelude . crc32(prelude) . headers . payload . crc32(all)

  with headers encoded as ``len(u8) name type(0x07) vlen(u16 BE) value``
  triples.  ``Records`` / ``Progress`` / ``Stats`` / ``Cont`` / ``End``
  event encoders plus ``iter_events`` (a CRC-checking decoder for tests
  and the bundled client).
* ``run_select`` — drives a compiled :class:`scan.ScanPlan` over a byte
  chunk iterator (the filer feeds ``_stream_range``'s prefetching
  generator straight in), gunzipping incrementally when asked, strictly
  validating UTF-8 (``InvalidTextEncoding``) and yielding framed events:
  one ``Records`` per scan batch (split at 1 MiB), an optional final
  ``Progress``, then ``Stats`` and ``End``.

Divergences from AWS are listed in docs/PARITY.md (SelectObjectContent
row): FileHeaderInfo is always USE, non-default CSV delimiters and
ScanRange are rejected with ``InvalidRequest``, and Progress — when
requested — is emitted once at end-of-stream rather than periodically.
"""

from __future__ import annotations

import codecs
import json
import struct
import xml.etree.ElementTree as ET
import zlib
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

from ..util.safe_xml import safe_fromstring
from .scan import ScanPlan
from .sql import SqlError, parse_sql

_RECORDS_FRAME = 1 << 20  # AWS caps Records payloads at 1 MiB


class SelectError(ValueError):
    """Protocol-level rejection; ``code`` is the S3 error code."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find(el: ET.Element, tag: str) -> Optional[ET.Element]:
    for c in el.iter():
        if _strip_ns(c.tag) == tag:
            return c
    return None


def _text(el: Optional[ET.Element], default: str = "") -> str:
    if el is None or el.text is None:
        return default
    return el.text


@dataclass
class SelectRequest:
    expression: str
    select: Optional[list] = None
    where: Optional[dict] = None
    limit: int = 0
    input_format: str = "csv"  # csv | json
    compression: str = "NONE"  # NONE | GZIP
    output_format: str = "csv"  # csv | json
    output_field_delim: str = ","
    output_record_delim: str = "\n"
    progress: bool = False
    backend: Optional[str] = field(default=None)


def parse_select_request(body: bytes) -> SelectRequest:
    """SelectObjectContentRequest XML → validated SelectRequest.

    Raises SelectError with the AWS code a real S3 endpoint would return
    for each malformation class; the callers map ``code`` through the
    gateway's error table (all land on HTTP 400)."""
    try:
        root = safe_fromstring(body)
    except ET.ParseError as e:
        raise SelectError("MalformedXML", f"unparseable request: {e}") from e
    if _strip_ns(root.tag) != "SelectObjectContentRequest":
        raise SelectError(
            "MalformedXML", f"unexpected root element {root.tag!r}"
        )

    expr = _text(_find(root, "Expression")).strip()
    if not expr:
        raise SelectError("MalformedXML", "Expression is required")
    etype = _text(_find(root, "ExpressionType"), "SQL").strip() or "SQL"
    if etype.upper() != "SQL":
        raise SelectError(
            "InvalidExpressionType", f"ExpressionType {etype!r} is not SQL"
        )

    inp = _find(root, "InputSerialization")
    if inp is None:
        raise SelectError("MalformedXML", "InputSerialization is required")
    compression = _text(_find(inp, "CompressionType"), "NONE").strip() or "NONE"
    if compression.upper() not in ("NONE", "GZIP"):
        raise SelectError(
            "InvalidCompressionFormat",
            f"CompressionType {compression!r} is not supported",
        )
    in_csv = _find(inp, "CSV")
    in_json = _find(inp, "JSON")
    if in_csv is not None:
        input_format = "csv"
        header_info = _text(
            _find(in_csv, "FileHeaderInfo"), "USE"
        ).strip().upper() or "USE"
        if header_info != "USE":
            raise SelectError(
                "InvalidRequest",
                "only FileHeaderInfo=USE is supported (column names come "
                "from the first line)",
            )
        fd = _text(_find(in_csv, "FieldDelimiter"), ",") or ","
        rd = _text(_find(in_csv, "RecordDelimiter"), "\n") or "\n"
        if fd != "," or rd != "\n":
            raise SelectError(
                "InvalidRequest",
                "only the default CSV delimiters (',' fields, LF records) "
                "are supported",
            )
    elif in_json is not None:
        # Type LINES and DOCUMENT both work: the scanner sniffs a leading
        # '[' and falls back to whole-document parsing on its own
        input_format = "json"
    else:
        raise SelectError(
            "MalformedXML", "InputSerialization needs a CSV or JSON element"
        )
    if _find(root, "ScanRange") is not None:
        raise SelectError("InvalidRequest", "ScanRange is not supported")

    out = _find(root, "OutputSerialization")
    output_format, ofd, ord_ = "csv", ",", "\n"
    if out is not None:
        out_json = _find(out, "JSON")
        out_csv = _find(out, "CSV")
        if out_json is not None:
            output_format = "json"
            ord_ = _text(_find(out_json, "RecordDelimiter"), "\n") or "\n"
        elif out_csv is not None:
            ofd = _text(_find(out_csv, "FieldDelimiter"), ",") or ","
            ord_ = _text(_find(out_csv, "RecordDelimiter"), "\n") or "\n"
    elif in_json is not None:
        output_format = "json"

    rp = _find(root, "RequestProgress")
    progress = (
        rp is not None
        and _text(_find(rp, "Enabled")).strip().lower() == "true"
    )

    try:
        select, where, limit = parse_sql(expr)
    except SqlError as e:
        raise SelectError("UnsupportedSqlStructure", str(e)) from e

    return SelectRequest(
        expression=expr,
        select=select,
        where=where,
        limit=limit,
        input_format=input_format,
        compression=compression.upper(),
        output_format=output_format,
        output_field_delim=ofd,
        output_record_delim=ord_,
        progress=progress,
    )


# --------------------------------------------------------------------------
# event-stream framing
# --------------------------------------------------------------------------


def encode_event(headers: dict[str, str], payload: bytes = b"") -> bytes:
    """One event-stream message: prelude + prelude CRC + headers +
    payload + message CRC (all big-endian, CRC32 per the AWS spec)."""
    hbuf = bytearray()
    for name, value in headers.items():
        nb, vb = name.encode("utf-8"), value.encode("utf-8")
        hbuf.append(len(nb))
        hbuf += nb
        hbuf.append(0x07)  # header value type 7: string
        hbuf += struct.pack(">H", len(vb))
        hbuf += vb
    total = 12 + len(hbuf) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hbuf))
    msg = prelude + struct.pack(">I", zlib.crc32(prelude)) + hbuf + payload
    return msg + struct.pack(">I", zlib.crc32(msg))


def _event(event_type: str, content_type: str, payload: bytes) -> bytes:
    headers = {":message-type": "event", ":event-type": event_type}
    if content_type:
        headers[":content-type"] = content_type
    return encode_event(headers, payload)


def records_event(data: bytes) -> bytes:
    return _event("Records", "application/octet-stream", data)


def continuation_event() -> bytes:
    return _event("Cont", "", b"")


def _xml_counts(tag: str, scanned: int, processed: int, returned: int) -> bytes:
    return (
        f"<{tag}><BytesScanned>{scanned}</BytesScanned>"
        f"<BytesProcessed>{processed}</BytesProcessed>"
        f"<BytesReturned>{returned}</BytesReturned></{tag}>"
    ).encode("utf-8")


def progress_event(scanned: int, processed: int, returned: int) -> bytes:
    return _event(
        "Progress", "text/xml",
        _xml_counts("Progress", scanned, processed, returned),
    )


def stats_event(scanned: int, processed: int, returned: int) -> bytes:
    return _event(
        "Stats", "text/xml", _xml_counts("Stats", scanned, processed, returned)
    )


def end_event() -> bytes:
    return _event("End", "", b"")


def error_event(code: str, message: str) -> bytes:
    """Mid-stream failure frame (AWS: message-type=error, no payload)."""
    return encode_event(
        {":message-type": "error", ":error-code": code,
         ":error-message": message},
    )


def iter_events(buf: bytes) -> Iterator[dict]:
    """Decode a concatenation of event-stream messages, verifying both
    CRCs; yields {"headers": {...}, "payload": bytes}.  Raises ValueError
    on any framing damage — the test suite's oracle and the bundled
    client's parser."""
    pos = 0
    while pos < len(buf):
        if len(buf) - pos < 16:
            raise ValueError("truncated event-stream prelude")
        total, hlen = struct.unpack_from(">II", buf, pos)
        (pcrc,) = struct.unpack_from(">I", buf, pos + 8)
        if pcrc != zlib.crc32(buf[pos : pos + 8]):
            raise ValueError("prelude CRC mismatch")
        if total < 16 or pos + total > len(buf):
            raise ValueError("event length exceeds buffer")
        (mcrc,) = struct.unpack_from(">I", buf, pos + total - 4)
        if mcrc != zlib.crc32(buf[pos : pos + total - 4]):
            raise ValueError("message CRC mismatch")
        headers: dict[str, str] = {}
        hp, hend = pos + 12, pos + 12 + hlen
        if hend > pos + total - 4:
            raise ValueError("headers overrun message")
        while hp < hend:
            nlen = buf[hp]
            name = buf[hp + 1 : hp + 1 + nlen].decode("utf-8")
            hp += 1 + nlen
            vtype = buf[hp]
            if vtype != 0x07:
                raise ValueError(f"unsupported header value type {vtype}")
            (vlen,) = struct.unpack_from(">H", buf, hp + 1)
            headers[name] = buf[hp + 3 : hp + 3 + vlen].decode("utf-8")
            hp += 3 + vlen
        yield {"headers": headers, "payload": buf[hend : pos + total - 4]}
        pos += total


# --------------------------------------------------------------------------
# execution
# --------------------------------------------------------------------------


def _gunzip_iter(chunks: Iterable[bytes]) -> Iterator[bytes]:
    # wbits=31: gzip container, incremental — a multi-chunk object is
    # never buffered compressed
    d = zlib.decompressobj(wbits=31)
    try:
        for chunk in chunks:
            got = d.decompress(chunk)
            if got:
                yield got
        tail = d.flush()
        if tail:
            yield tail
        if not d.eof:
            raise SelectError(
                "InvalidCompressionFormat", "truncated gzip stream"
            )
    except zlib.error as e:
        raise SelectError(
            "InvalidCompressionFormat", f"bad gzip data: {e}"
        ) from e


class _CountingUtf8Iter:
    """Pass-through chunk iterator that counts raw bytes and strictly
    validates UTF-8 across chunk boundaries (the scanner itself decodes
    with errors='replace'; S3 Select must reject instead)."""

    def __init__(self, chunks: Iterable[bytes]):
        self._chunks = iter(chunks)
        self._dec = codecs.getincrementaldecoder("utf-8")()
        self.nbytes = 0

    def __iter__(self):
        for chunk in self._chunks:
            self.nbytes += len(chunk)
            try:
                self._dec.decode(chunk, False)
            except UnicodeDecodeError as e:
                raise SelectError(
                    "InvalidTextEncoding",
                    f"object is not valid UTF-8 at byte "
                    f"{self.nbytes - len(chunk) + e.start}",
                ) from e
            yield chunk
        try:
            self._dec.decode(b"", True)
        except UnicodeDecodeError as e:
            raise SelectError(
                "InvalidTextEncoding",
                "object ends inside a multi-byte UTF-8 sequence",
            ) from e


def _serialize_batch(rows: list[dict], req: SelectRequest) -> bytes:
    if req.output_format == "json":
        rd = req.output_record_delim
        return "".join(json.dumps(r) + rd for r in rows).encode("utf-8")
    import csv as _csv
    import io as _io

    buf = _io.StringIO()
    w = _csv.writer(
        buf, delimiter=req.output_field_delim,
        lineterminator=req.output_record_delim,
    )
    for r in rows:
        w.writerow([
            "" if v is None
            else ("true" if v is True else "false") if isinstance(v, bool)
            else v
            for v in r.values()
        ])
    return buf.getvalue().encode("utf-8")


def run_select(
    chunks: Iterable[bytes], req: SelectRequest,
    backend: Optional[str] = None,
) -> Iterator[bytes]:
    """Drive a scan plan over a chunk stream → framed response events.

    Yields encoded event-stream messages; raises SelectError before the
    first yield for malformed input discovered up front, and mid-stream
    for damage found while scanning (callers that already sent headers
    can close with ``error_event``)."""
    plan = ScanPlan(
        select=req.select, where=req.where, limit=req.limit,
        input_format=req.input_format, backend=backend or req.backend,
    )
    raw_counter = None
    if req.compression == "GZIP":
        # BytesScanned counts raw (compressed) object bytes; UTF-8 is
        # validated on the DECOMPRESSED text, which is what the scanner
        # actually reads
        raw_counter = _RawCounter(chunks)
        text = _CountingUtf8Iter(_gunzip_iter(raw_counter))
    else:
        text = _CountingUtf8Iter(chunks)
    returned = 0
    for batch in plan.scan_iter(text):
        if not batch:
            continue
        data = _serialize_batch(batch, req)
        for off in range(0, len(data), _RECORDS_FRAME):
            frame = data[off : off + _RECORDS_FRAME]
            returned += len(frame)
            yield records_event(frame)
    scanned = raw_counter.nbytes if raw_counter is not None else text.nbytes
    processed = plan.stats["bytes_scanned"]
    if req.progress:
        yield progress_event(scanned, processed, returned)
    yield stats_event(scanned, processed, returned)
    yield end_event()


class _RawCounter:
    """Counts compressed bytes on their way into the gunzipper (the
    Stats frame's BytesScanned)."""

    def __init__(self, chunks: Iterable[bytes]):
        self._chunks = iter(chunks)
        self.nbytes = 0

    def __iter__(self):
        for chunk in self._chunks:
            self.nbytes += len(chunk)
            yield chunk


def select_to_bytes(
    chunks: Iterable[bytes], body_xml: bytes, backend: Optional[str] = None
) -> bytes:
    """Parse + run + frame in one buffered call — the filer's unit of
    work (its JSON handler replies with complete bodies)."""
    req = parse_select_request(body_xml)
    return b"".join(run_select(chunks, req, backend=backend))
