"""VolumeLayout: writable/readonly volume sets per (collection, rp, ttl).

Mirrors `weed/topology/volume_layout.go`: tracks vid → replica locations,
keeps the writable list consistent with replica counts and sizes, and picks
random writable volumes for assignment.
"""

from __future__ import annotations

import random
import threading
from typing import TYPE_CHECKING, Optional

from ..storage.replica_placement import ReplicaPlacement
from ..storage.ttl import TTL
from ..util.locks import make_rlock

if TYPE_CHECKING:
    from .topology import DataNode, VolumeInfo


class VolumeLayout:
    def __init__(
        self,
        rp: ReplicaPlacement,
        ttl: TTL,
        volume_size_limit: int,
    ):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.vid2location: dict[int, list["DataNode"]] = {}
        self.writables: list[int] = []
        self.readonly_volumes: set[int] = set()
        self.oversized_volumes: set[int] = set()
        self._lock = make_rlock("VolumeLayout._lock")

    # -- registration (volume_layout.go:104-200) -----------------------------
    def register_volume(self, vi: "VolumeInfo", dn: "DataNode") -> None:
        with self._lock:
            locs = self.vid2location.setdefault(vi.id, [])
            if dn not in locs:
                locs.append(dn)
            self.ensure_correct_writables(vi)

    def unregister_volume(self, vi: "VolumeInfo", dn: "DataNode") -> None:
        with self._lock:
            locs = self.vid2location.get(vi.id)
            if locs and dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid2location.pop(vi.id, None)
                self._remove_from_writable(vi.id)
            else:
                self._ensure_writable_state(vi.id)

    def ensure_correct_writables(self, vi: "VolumeInfo") -> None:
        with self._lock:
            if vi.read_only:
                self.readonly_volumes.add(vi.id)
            else:
                self.readonly_volumes.discard(vi.id)
            if vi.size >= self.volume_size_limit:
                self.oversized_volumes.add(vi.id)
            else:
                # a vacuumed volume can shrink back under the limit
                self.oversized_volumes.discard(vi.id)
            self._ensure_writable_state(vi.id)

    def _ensure_writable_state(self, vid: int) -> None:
        locs = self.vid2location.get(vid, [])
        enough_replicas = len(locs) >= self.rp.copy_count()
        writable = (
            enough_replicas
            and vid not in self.readonly_volumes
            and vid not in self.oversized_volumes
        )
        if writable:
            if vid not in self.writables:
                self.writables.append(vid)
        else:
            self._remove_from_writable(vid)

    def _remove_from_writable(self, vid: int) -> None:
        if vid in self.writables:
            self.writables.remove(vid)

    def set_volume_unavailable(self, vid: int, dn: "DataNode") -> bool:
        """Node lost (volume_layout.go:357): drop this replica; volume leaves
        the writable set when replicas fall below the placement count."""
        with self._lock:
            locs = self.vid2location.get(vid)
            if locs and dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid2location.pop(vid, None)
            self._ensure_writable_state(vid)
            return vid in self.writables

    def set_volume_readonly(self, vid: int) -> None:
        with self._lock:
            self.readonly_volumes.add(vid)
            self._remove_from_writable(vid)

    # -- assignment (volume_layout.go:267-300) -------------------------------
    def pick_for_write(
        self, data_center: str = ""
    ) -> tuple[int, list["DataNode"]]:
        with self._lock:
            if not self.writables:
                raise NoWritableVolumesError("no more writable volumes")
            if not data_center:
                vid = random.choice(self.writables)
                return vid, list(self.vid2location[vid])
            candidates = []
            for vid in self.writables:
                locs = self.vid2location.get(vid, [])
                if any(dn.get_data_center().id == data_center for dn in locs):
                    candidates.append((vid, locs))
            if not candidates:
                raise NoWritableVolumesError(
                    f"no writable volumes in data center {data_center}"
                )
            vid, locs = random.choice(candidates)
            return vid, list(locs)

    def active_volume_count(self) -> int:
        return len(self.writables)

    def stats(self) -> dict:
        with self._lock:
            return {
                "replication": str(self.rp),
                "ttl": str(self.ttl),
                "writables": sorted(self.writables),
                "readonly": sorted(self.readonly_volumes),
                "oversized": sorted(self.oversized_volumes),
                "volume_count": len(self.vid2location),
            }


class NoWritableVolumesError(Exception):
    pass
