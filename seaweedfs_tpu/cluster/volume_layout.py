"""VolumeLayout: writable/readonly volume sets per (collection, rp, ttl).

Mirrors `weed/topology/volume_layout.go`: tracks vid → replica locations and
keeps the writable list consistent with replica counts and sizes.  Where the
reference picks writables uniformly at random, this layout weights the pick
by free space over volume heat (the EWMA counters volume servers ship in
heartbeats — stats/heat.py) and skips volumes whose every replica sits on an
overloaded node, so zipfian read storms stop attracting new writes to the
nodes already melting (the f4 observation).  The divergence is recorded in
docs/PARITY.md.
"""

from __future__ import annotations

import random
import threading
from typing import TYPE_CHECKING, Optional

from ..storage.replica_placement import ReplicaPlacement
from ..storage.ttl import TTL
from ..util.locks import make_rlock

if TYPE_CHECKING:
    from .topology import DataNode, VolumeInfo

# module-level RNG so placement is seedable in tests (seed_placement) and
# no pick path reaches for the process-global `random` state
_rng = random.Random()

# a node is overloaded when its heat exceeds this multiple of the mean
# node heat among the current candidates' replica holders
OVERLOAD_FACTOR = 2.0


def seed_placement(seed=None) -> None:
    """Seed the placement RNG — deterministic writable picks for tests."""
    _rng.seed(seed)


# -- lifecycle heat thresholds (f4's hot→warm→cold bands) ---------------------
# The lifecycle controller (cluster/lifecycle.py) classifies every volume by
# its EWMA heat against three thresholds, env-tunable so probes and small
# clusters can shrink the bands:
#   heat >  ceiling                         hot   — un-EC / replica-boost
#   floor <= heat <= ceiling                warm  — leave alone
#   tier_floor <= heat < floor (streak)     cool  — fleet-EC, replicas reclaimed
#   heat <  tier_floor         (streak)     cold  — tier the bytes to S3
def heat_floor() -> float:
    """Below this a plain volume is cooling toward the EC (warm) tier."""
    import os

    from ..util.parsers import tolerant_ufloat

    return tolerant_ufloat(os.environ.get("SWEED_HEAT_FLOOR", ""), 0.05)


def heat_ceiling() -> float:
    """Above this an EC volume is hot enough to un-EC (or replica-boost)."""
    import os

    from ..util.parsers import tolerant_ufloat

    return tolerant_ufloat(os.environ.get("SWEED_HEAT_CEILING", ""), 50.0)


def tier_floor() -> float:
    """Below this a volume is cold enough for the S3 tier (must be below
    heat_floor to mean anything)."""
    import os

    from ..util.parsers import tolerant_ufloat

    return tolerant_ufloat(os.environ.get("SWEED_TIER_FLOOR", ""), 0.005)


def classify_heat(
    heat: float,
    floor: Optional[float] = None,
    ceiling: Optional[float] = None,
    cold: Optional[float] = None,
) -> str:
    """Heat value → band name: "hot" | "warm" | "cool" | "cold"."""
    floor = heat_floor() if floor is None else floor
    ceiling = heat_ceiling() if ceiling is None else ceiling
    cold = tier_floor() if cold is None else cold
    if heat > ceiling:
        return "hot"
    if heat >= floor:
        return "warm"
    if heat >= cold:
        return "cool"
    return "cold"


class VolumeLayout:
    def __init__(
        self,
        rp: ReplicaPlacement,
        ttl: TTL,
        volume_size_limit: int,
    ):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.vid2location: dict[int, list["DataNode"]] = {}
        self.writables: list[int] = []
        self.readonly_volumes: set[int] = set()
        self.oversized_volumes: set[int] = set()
        # vid → read+write heat, refreshed from every heartbeat's
        # VolumeInfo; feeds the weighted pick below
        self.volume_heat: dict[int, float] = {}
        self._lock = make_rlock("VolumeLayout._lock")

    # -- registration (volume_layout.go:104-200) -----------------------------
    def register_volume(self, vi: "VolumeInfo", dn: "DataNode") -> None:
        with self._lock:
            locs = self.vid2location.setdefault(vi.id, [])
            if dn not in locs:
                locs.append(dn)
            self.ensure_correct_writables(vi)

    def unregister_volume(self, vi: "VolumeInfo", dn: "DataNode") -> None:
        with self._lock:
            locs = self.vid2location.get(vi.id)
            if locs and dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid2location.pop(vi.id, None)
                self.volume_heat.pop(vi.id, None)
                self._remove_from_writable(vi.id)
            else:
                self._ensure_writable_state(vi.id)

    def ensure_correct_writables(self, vi: "VolumeInfo") -> None:
        with self._lock:
            self.volume_heat[vi.id] = vi.read_heat + vi.write_heat
            if vi.read_only:
                self.readonly_volumes.add(vi.id)
            else:
                self.readonly_volumes.discard(vi.id)
            if vi.size >= self.volume_size_limit:
                self.oversized_volumes.add(vi.id)
            else:
                # a vacuumed volume can shrink back under the limit
                self.oversized_volumes.discard(vi.id)
            self._ensure_writable_state(vi.id)

    def _ensure_writable_state(self, vid: int) -> None:
        locs = self.vid2location.get(vid, [])
        enough_replicas = len(locs) >= self.rp.copy_count()
        writable = (
            enough_replicas
            and vid not in self.readonly_volumes
            and vid not in self.oversized_volumes
        )
        if writable:
            if vid not in self.writables:
                self.writables.append(vid)
        else:
            self._remove_from_writable(vid)

    def _remove_from_writable(self, vid: int) -> None:
        if vid in self.writables:
            self.writables.remove(vid)

    def set_volume_unavailable(self, vid: int, dn: "DataNode") -> bool:
        """Node lost (volume_layout.go:357): drop this replica; volume leaves
        the writable set when replicas fall below the placement count."""
        with self._lock:
            locs = self.vid2location.get(vid)
            if locs and dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid2location.pop(vid, None)
                self.volume_heat.pop(vid, None)
            self._ensure_writable_state(vid)
            return vid in self.writables

    def set_volume_readonly(self, vid: int) -> None:
        with self._lock:
            self.readonly_volumes.add(vid)
            self._remove_from_writable(vid)

    # -- assignment (volume_layout.go:267-300, heat-weighted divergence) -----
    def pick_for_write(
        self, data_center: str = ""
    ) -> tuple[int, list["DataNode"]]:
        with self._lock:
            if not self.writables:
                raise NoWritableVolumesError("no more writable volumes")
            candidates = []
            for vid in self.writables:
                locs = self.vid2location.get(vid, [])
                if data_center and not any(
                    dn.get_data_center().id == data_center for dn in locs
                ):
                    continue
                candidates.append((vid, locs))
            if not candidates:
                raise NoWritableVolumesError(
                    f"no writable volumes in data center {data_center}"
                )
            candidates = self._drop_overloaded(candidates)
            vid, locs = self._weighted_pick(candidates)
            return vid, list(locs)

    def _drop_overloaded(self, candidates):
        """Skip volumes whose every replica sits on an overloaded node
        (node heat > OVERLOAD_FACTOR × mean over candidate holders).
        Falls back to the full list when the filter would empty it —
        degraded placement still beats NoWritableVolumesError."""
        node_heat: dict["DataNode", float] = {}
        for vid, locs in candidates:
            h = self.volume_heat.get(vid, 0.0)
            for dn in locs:
                node_heat[dn] = node_heat.get(dn, 0.0) + h
        if len(node_heat) < 2:
            return candidates
        mean = sum(node_heat.values()) / len(node_heat)
        if mean <= 0.0:
            return candidates
        overloaded = {
            dn for dn, h in node_heat.items() if h > OVERLOAD_FACTOR * mean
        }
        if not overloaded:
            return candidates
        kept = [
            (vid, locs)
            for vid, locs in candidates
            if locs and not all(dn in overloaded for dn in locs)
        ]
        return kept or candidates

    def _weighted_pick(self, candidates):
        """Sample one candidate ∝ free-space / (1 + heat): cold volumes on
        roomy nodes absorb new writes, hot ones cool off.  With no heat
        and uniform free space this degrades to the reference's uniform
        random pick."""
        weights = []
        for vid, locs in candidates:
            free = min((dn.free_space() for dn in locs), default=0)
            heat = self.volume_heat.get(vid, 0.0)
            weights.append((1.0 + max(0, free)) / (1.0 + heat))
        total = sum(weights)
        if total <= 0.0:
            return candidates[_rng.randrange(len(candidates))]
        r = _rng.random() * total
        for pair, w in zip(candidates, weights):
            r -= w
            if r <= 0.0:
                return pair
        return candidates[-1]

    def active_volume_count(self) -> int:
        return len(self.writables)

    def stats(self) -> dict:
        with self._lock:
            return {
                "replication": str(self.rp),
                "ttl": str(self.ttl),
                "writables": sorted(self.writables),
                "readonly": sorted(self.readonly_volumes),
                "oversized": sorted(self.oversized_volumes),
                "volume_count": len(self.vid2location),
                "heat": {
                    str(vid): round(h, 3)
                    for vid, h in sorted(self.volume_heat.items())
                    if h > 0.0
                },
            }


class NoWritableVolumesError(Exception):
    pass
