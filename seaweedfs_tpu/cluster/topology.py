"""Topology tree: DataCenter → Rack → DataNode, with volume/EC registries.

Mirrors `weed/topology/topology.go`, `node.go`, `data_node.go`,
`topology_ec.go`. The tree tracks capacity (volume slots) for placement; the
topology is rebuilt from heartbeats, never persisted (raft in the reference
replicates only the sequence counter — raft_server.go:30).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..storage.replica_placement import ReplicaPlacement
from ..storage.ttl import TTL
from ..util.locks import make_rlock
from ..util.racecheck import instrument


@dataclass
class VolumeInfo:
    """What the master knows about one volume replica (storage.VolumeInfo)."""

    id: int
    size: int = 0
    collection: str = ""
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    version: int = 3
    ttl: TTL = field(default_factory=TTL)
    compact_revision: int = 0
    # decayed op counters from the volume server's EWMA heat accounting
    # (stats/heat.py); old servers simply never report them
    read_heat: float = 0.0
    write_heat: float = 0.0
    # lifecycle signals: .dat lives on an S3-class remote backend, and how
    # many needles the background scrub flagged as CRC-corrupt
    remote_tier: bool = False
    corrupt_needles: int = 0

    @classmethod
    def from_heartbeat(cls, m: dict) -> "VolumeInfo":
        from ..storage.ttl import load_ttl_from_uint32

        return cls(
            id=m["id"],
            size=m.get("size", 0),
            collection=m.get("collection", ""),
            file_count=m.get("file_count", 0),
            delete_count=m.get("delete_count", 0),
            deleted_byte_count=m.get("deleted_byte_count", 0),
            read_only=m.get("read_only", False),
            replica_placement=ReplicaPlacement.from_byte(
                m.get("replica_placement", 0)
            ),
            version=m.get("version", 3),
            ttl=load_ttl_from_uint32(m.get("ttl", 0)),
            compact_revision=m.get("compact_revision", 0),
            read_heat=m.get("read_heat", 0.0),
            write_heat=m.get("write_heat", 0.0),
            remote_tier=m.get("remote_tier", False),
            corrupt_needles=m.get("corrupt_needles", 0),
        )


class Node:
    """Tree node with capacity counting (topology/node.go)."""

    def __init__(self, node_id: str):
        self.id = node_id
        self.children: dict[str, "Node"] = {}
        self.parent: Optional["Node"] = None
        self._max_volume_count = 0

    # capacity aggregates are recomputed on demand (simpler than the
    # reference's up-adjusting deltas; topologies are small)
    def max_volume_count(self) -> int:
        if not self.children:
            return self._max_volume_count
        return sum(c.max_volume_count() for c in self.children.values())

    def volume_count(self) -> int:
        if not self.children:
            return 0  # a leaf Rack/DC holds nothing; DataNode overrides
        return sum(c.volume_count() for c in self.children.values())

    def free_space(self) -> int:
        return self.max_volume_count() - self.volume_count()

    def is_data_node(self) -> bool:
        return False

    def get_or_create(self, node_id: str, factory) -> "Node":
        child = self.children.get(node_id)
        if child is None:
            child = factory(node_id)
            child.parent = self
            self.children[node_id] = child
        return child

    def pick_nodes_by_weight(
        self, count: int, filter_fn: Callable[["Node"], Optional[str]]
    ) -> tuple["Node", list["Node"]]:
        """Randomly pick `count` eligible children weighted by free space
        (node.go PickNodesByWeight): returns (main, others). Raises if fewer
        than count eligible."""
        candidates = []
        errs = []
        for c in self.children.values():
            err = filter_fn(c)
            if err is None:
                candidates.append(c)
            else:
                errs.append(f"{c.id}: {err}")
        if len(candidates) < count:
            raise NoFreeSpaceError(
                f"only {len(candidates)} of {len(self.children)} nodes eligible "
                f"under {self.id}, need {count}: " + "; ".join(errs[:5])
            )
        weights = [max(c.free_space(), 1) for c in candidates]
        picked: list[Node] = []
        pool = list(zip(candidates, weights))
        for _ in range(count):
            total = sum(w for _, w in pool)
            r = random.uniform(0, total)
            acc = 0.0
            for i, (c, w) in enumerate(pool):
                acc += w
                if r <= acc:
                    picked.append(c)
                    pool.pop(i)
                    break
        return picked[0], picked[1:]

    def reserve_one_volume(self) -> "DataNode":
        """Random free-space-weighted descent to a data node with a slot
        (node.go ReserveOneVolume)."""
        if self.is_data_node():
            if self.free_space() <= 0:
                raise NoFreeSpaceError(f"no slots on {self.id}")
            return self  # type: ignore[return-value]
        eligible = [c for c in self.children.values() if c.free_space() > 0]
        if not eligible:
            raise NoFreeSpaceError(f"no free slots under {self.id}")
        weights = [c.free_space() for c in eligible]
        chosen = random.choices(eligible, weights=weights)[0]
        return chosen.reserve_one_volume()


class NoFreeSpaceError(Exception):
    pass


class DataNode(Node):
    """One volume server (topology/data_node.go)."""

    def __init__(self, node_id: str):
        super().__init__(node_id)
        self.ip = ""
        self.port = 0
        self.public_url = ""
        self.volumes: dict[int, VolumeInfo] = {}
        self.ec_shards: dict[int, int] = {}  # vid → shard bit mask
        # lifecycle signals riding the EC heartbeat entries: decayed read
        # heat per EC volume and scrub-flagged corrupt shard ids on this node
        self.ec_read_heat: dict[int, float] = {}
        self.ec_corrupt: dict[int, list[int]] = {}
        self.last_seen = 0.0
        self.pulse_seconds = 5.0  # node-reported beat interval

    def is_data_node(self) -> bool:
        return True

    def url(self) -> str:
        return self.public_url or f"{self.ip}:{self.port}"

    def grpc_url(self) -> str:
        return f"{self.ip}:{self.port + 10000}"

    def volume_count(self) -> int:
        # derived from the volumes dict on demand: a cached count would
        # be one more field every sync/growth path must keep coherent
        # across the handler and background domains
        return len(self.volumes)

    def get_rack(self) -> "Rack":
        return self.parent  # type: ignore[return-value]

    def get_data_center(self) -> "DataCenter":
        return self.parent.parent  # type: ignore[return-value]


class Rack(Node):
    def new_data_node(
        self, node_id: str, ip: str, port: int, public_url: str, max_volumes: int
    ) -> DataNode:
        dn = self.get_or_create(node_id, DataNode)
        assert isinstance(dn, DataNode)
        dn.ip, dn.port, dn.public_url = ip, port, public_url
        dn._max_volume_count = max_volumes
        return dn


class DataCenter(Node):
    def get_or_create_rack(self, rack_id: str) -> Rack:
        r = self.get_or_create(rack_id, Rack)
        assert isinstance(r, Rack)
        return r


@instrument
class Topology(Node):
    def __init__(self, volume_size_limit: int = 30 * 1024 * 1024 * 1024):
        super().__init__("topo")
        self.volume_size_limit = volume_size_limit
        self._lock = make_rlock("Topology._lock")
        # (collection, rp_str, ttl_str) → VolumeLayout
        from .volume_layout import VolumeLayout

        self._VolumeLayout = VolumeLayout
        self.layouts: dict[tuple[str, str, str], "VolumeLayout"] = {}
        # vid → set of DataNode holding EC shards: vid → {shard_id → [nodes]}
        self.ec_shard_locations: dict[int, dict[int, list[DataNode]]] = {}
        self.max_volume_id = 0

    # -- tree building -------------------------------------------------------
    def get_or_create_data_center(self, dc_id: str) -> DataCenter:
        dc = self.get_or_create(dc_id, DataCenter)
        assert isinstance(dc, DataCenter)
        return dc

    def data_nodes(self) -> list[DataNode]:
        out = []
        for dc in self.children.values():
            for rack in dc.children.values():
                out.extend(
                    n for n in rack.children.values() if isinstance(n, DataNode)
                )
        return out

    # -- layouts -------------------------------------------------------------
    def get_volume_layout(
        self, collection: str, rp: ReplicaPlacement, ttl: TTL
    ) -> "VolumeLayout":
        key = (collection, str(rp), str(ttl))
        with self._lock:
            layout = self.layouts.get(key)
            if layout is None:
                layout = self._VolumeLayout(rp, ttl, self.volume_size_limit)
                self.layouts[key] = layout
            return layout

    def collection_names(self) -> list[str]:
        return sorted({k[0] for k in self.layouts if k[0]})

    def delete_collection(self, collection: str) -> list[int]:
        """Drop all layouts of a collection; returns affected vids."""
        with self._lock:
            vids = []
            for key in [k for k in self.layouts if k[0] == collection]:
                vids.extend(self.layouts[key].vid2location.keys())
                del self.layouts[key]
            return vids

    # -- heartbeat sync (topology.go:205-260) --------------------------------
    def sync_data_node_registration(
        self, dn: DataNode, volumes: list[dict]
    ) -> tuple[list[VolumeInfo], list[VolumeInfo]]:
        """Full heartbeat: replace dn's volume list. Returns (new, deleted)."""
        with self._lock:
            incoming = {m["id"]: VolumeInfo.from_heartbeat(m) for m in volumes}
            new_vis, deleted_vis = [], []
            for vid, vi in incoming.items():
                if vid not in dn.volumes:
                    new_vis.append(vi)
                self.max_volume_id = max(self.max_volume_id, vid)
            for vid, vi in dn.volumes.items():
                if vid not in incoming:
                    deleted_vis.append(vi)
            dn.volumes = incoming
            for vi in new_vis:
                self._register_volume(vi, dn)
            for vi in deleted_vis:
                self._unregister_volume(vi, dn)
            # refresh writability/size state for still-present volumes
            for vi in incoming.values():
                layout = self.get_volume_layout(
                    vi.collection, vi.replica_placement, vi.ttl
                )
                layout.ensure_correct_writables(vi)
            return new_vis, deleted_vis

    def incremental_sync(
        self, dn: DataNode, new_volumes: list[dict], deleted_volumes: list[dict]
    ) -> None:
        with self._lock:
            for m in new_volumes:
                vi = VolumeInfo.from_heartbeat(m)
                dn.volumes[vi.id] = vi
                self.max_volume_id = max(self.max_volume_id, vi.id)
                self._register_volume(vi, dn)
            for m in deleted_volumes:
                vi = VolumeInfo.from_heartbeat(m)
                dn.volumes.pop(vi.id, None)
                self._unregister_volume(vi, dn)

    def _register_volume(self, vi: VolumeInfo, dn: DataNode) -> None:
        layout = self.get_volume_layout(vi.collection, vi.replica_placement, vi.ttl)
        layout.register_volume(vi, dn)

    def _unregister_volume(self, vi: VolumeInfo, dn: DataNode) -> None:
        layout = self.get_volume_layout(vi.collection, vi.replica_placement, vi.ttl)
        layout.unregister_volume(vi, dn)

    def unregister_data_node(self, dn: DataNode) -> list[int]:
        """Node lost: mark its volumes unavailable. Returns affected vids."""
        with self._lock:
            affected = []
            for vi in dn.volumes.values():
                layout = self.get_volume_layout(
                    vi.collection, vi.replica_placement, vi.ttl
                )
                layout.set_volume_unavailable(vi.id, dn)
                affected.append(vi.id)
            for vid in list(dn.ec_shards):
                self.unregister_ec_shards(vid, dn)
                affected.append(vid)
            dn.volumes = {}
            dn.ec_shards = {}
            dn.ec_read_heat = {}
            dn.ec_corrupt = {}
            if dn.parent:
                dn.parent.children.pop(dn.id, None)
            return affected

    # -- lookup --------------------------------------------------------------
    def lookup(self, collection: str, vid: int) -> list[DataNode]:
        with self._lock:
            if collection:
                keys = [k for k in self.layouts if k[0] == collection]
            else:
                keys = list(self.layouts)
            for key in keys:
                loc = self.layouts[key].vid2location.get(vid)
                if loc:
                    return list(loc)
            return []

    # -- EC shard registry (topology_ec.go:97-160) ---------------------------
    def sync_data_node_ec_shards(
        self, dn: DataNode, shards: list[dict]
    ) -> tuple[list[dict], list[dict]]:
        with self._lock:
            incoming: dict[int, int] = {}
            heat: dict[int, float] = {}
            corrupt: dict[int, set[int]] = {}
            for s in shards:  # OR-merge: one entry per disk location
                vid = s["id"]
                incoming[vid] = incoming.get(vid, 0) | s.get("ec_index_bits", 0)
                h = s.get("read_heat", 0.0)
                if h > heat.get(vid, 0.0):
                    heat[vid] = h
                if s.get("corrupt_shards"):
                    corrupt.setdefault(vid, set()).update(s["corrupt_shards"])
            new_s, deleted_s = [], []
            for vid, bits in incoming.items():
                old = dn.ec_shards.get(vid, 0)
                if bits & ~old:
                    new_s.append({"id": vid, "ec_index_bits": bits & ~old})
            for vid, bits in dn.ec_shards.items():
                gone = bits & ~incoming.get(vid, 0)
                if gone:
                    deleted_s.append({"id": vid, "ec_index_bits": gone})
            # rebuild registry entries for this node
            for vid in set(dn.ec_shards) | set(incoming):
                self._set_ec_shards(vid, dn, incoming.get(vid, 0))
            dn.ec_shards = incoming
            dn.ec_read_heat = heat
            dn.ec_corrupt = {v: sorted(s) for v, s in corrupt.items()}
            return new_s, deleted_s

    def _set_ec_shards(self, vid: int, dn: DataNode, bits: int) -> None:
        by_shard = self.ec_shard_locations.setdefault(vid, {})
        for sid in range(64):
            has = bool(bits & (1 << sid))
            nodes = by_shard.get(sid)
            if nodes is None:
                if not has:
                    continue
                nodes = by_shard.setdefault(sid, [])
            present = dn in nodes
            if has and not present:
                nodes.append(dn)
            elif not has and present:
                nodes.remove(dn)
            if not nodes:
                by_shard.pop(sid, None)
        if not by_shard:
            self.ec_shard_locations.pop(vid, None)

    def register_ec_shards(self, vid: int, dn: DataNode, bits: int) -> None:
        with self._lock:
            self._set_ec_shards(vid, dn, dn.ec_shards.get(vid, 0) | bits)
            dn.ec_shards[vid] = dn.ec_shards.get(vid, 0) | bits

    def unregister_ec_shards(self, vid: int, dn: DataNode, bits: int = ~0) -> None:
        with self._lock:
            remaining = dn.ec_shards.get(vid, 0) & ~bits
            self._set_ec_shards(vid, dn, remaining)
            if remaining:
                dn.ec_shards[vid] = remaining
            else:
                dn.ec_shards.pop(vid, None)

    def lookup_ec_shards(self, vid: int) -> dict[int, list[DataNode]]:
        with self._lock:
            return {
                sid: list(nodes)
                for sid, nodes in self.ec_shard_locations.get(vid, {}).items()
                if nodes
            }

    def checkpoint_max_volume_id(self, vid: int) -> None:
        """Follower-side: adopt the leader's volume-id high-water mark so a
        failover never re-allocates a vid (rides leader beats)."""
        with self._lock:
            self.max_volume_id = max(self.max_volume_id, vid)

    def next_volume_id(self) -> int:
        with self._lock:
            self.max_volume_id += 1
            return self.max_volume_id
