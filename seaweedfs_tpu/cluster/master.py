"""Master core: assignment, lookup, heartbeat intake, location push, locks.

The transport-agnostic heart of `weed/server/master_server.go` +
`master_grpc_server*.go`: volume servers feed heartbeats in, clients call
assign/lookup, subscribers receive volume-location deltas (the KeepConnected
stream), the admin shell takes the exclusive lock, and a vacuum scan drives
compaction through injected callbacks. HTTP/gRPC wrappers live in
`seaweedfs_tpu.server`.
"""

from __future__ import annotations

import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..storage.file_id import FileId
from ..util import glog
from ..storage.replica_placement import ReplicaPlacement
from ..storage.ttl import EMPTY_TTL, read_ttl
from .sequence import MemorySequencer
from .topology import DataNode, Topology
from .volume_growth import VolumeGrowOption, VolumeGrowth
from .volume_layout import NoWritableVolumesError
from ..util.locks import make_condition, make_rlock


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    count: int
    replicas: list[str] = field(default_factory=list)


# push(event) where event = {"vid":…, "urls":[…], "deleted":bool}
LocationSubscriber = Callable[[dict], None]


class Master:
    def __init__(
        self,
        volume_size_limit: int = 30 * 1024 * 1024 * 1024,
        default_replication: str = "000",
        allocate_volume: Optional[Callable] = None,
        garbage_threshold: float = 0.3,
        pulse_seconds: float = 5.0,
    ):
        self.topo = Topology(volume_size_limit)
        self.sequencer = MemorySequencer()
        self.default_replication = ReplicaPlacement.from_string(default_replication)
        self.garbage_threshold = garbage_threshold
        self.pulse_seconds = pulse_seconds
        self.vg = VolumeGrowth(
            allocate_volume or self._reject_allocate,
            on_register=lambda vid, dn: self._notify(vid, dn, deleted=False),
        )
        self._subscribers: dict[str, LocationSubscriber] = {}
        self._admin_lock_token: Optional[str] = None
        self._admin_lock_ts = 0.0
        self._admin_lock_client = ""
        self._lock = make_rlock("Master._lock")
        # versioned VolumeLocation delta log for remote KeepConnected
        # subscribers (wdclient long-polls /cluster/watch against this)
        self._loc_version = 0
        self._loc_log: deque = deque(maxlen=4096)
        self._loc_cond = make_condition(self._lock)

    @staticmethod
    def _reject_allocate(dn, vid, option):
        raise RuntimeError("no allocate_volume callback wired to master")

    # -- heartbeat intake (master_grpc_server.go:20-130) ---------------------
    def register_data_node(
        self,
        ip: str,
        port: int,
        public_url: str = "",
        data_center: str = "DefaultDataCenter",
        rack: str = "DefaultRack",
        max_volume_count: int = 7,
    ) -> DataNode:
        dc = self.topo.get_or_create_data_center(data_center)
        r = dc.get_or_create_rack(rack)
        dn = r.new_data_node(f"{ip}:{port}", ip, port, public_url, max_volume_count)
        dn.last_seen = time.time()
        return dn

    def handle_heartbeat(self, dn: DataNode, hb: dict) -> dict:
        """Full or delta heartbeat dict (Store.collect_heartbeat shape).
        Returns the ack (volume size limit + leader).

        Holds the master lock: a full sync racing a concurrent assign/grow
        (which registers new volumes under the same lock) must not replace
        the node's volume list with a pre-grow snapshot and unregister a
        volume whose fid was just handed out."""
        with self._lock:
            return self._handle_heartbeat_locked(dn, hb)

    def _handle_heartbeat_locked(self, dn: DataNode, hb: dict) -> dict:
        dn.last_seen = time.time()
        if "pulse_seconds" in hb:
            dn.pulse_seconds = float(hb["pulse_seconds"])
        if "max_file_key" in hb:
            self.sequencer.set_max(hb["max_file_key"])
        if "max_volume_count" in hb:
            dn._max_volume_count = hb["max_volume_count"]
        if "volumes" in hb:
            new_vis, deleted_vis = self.topo.sync_data_node_registration(
                dn, hb["volumes"]
            )
            for vi in new_vis:
                self._notify(vi.id, dn, deleted=False)
            for vi in deleted_vis:
                self._notify(vi.id, dn, deleted=True)
        if hb.get("new_volumes") or hb.get("deleted_volumes"):
            self.topo.incremental_sync(
                dn, hb.get("new_volumes", []), hb.get("deleted_volumes", [])
            )
            for m in hb.get("new_volumes", []):
                self._notify(m["id"], dn, deleted=False)
            for m in hb.get("deleted_volumes", []):
                self._notify(m["id"], dn, deleted=True)
        if "ec_shards" in hb:
            self.topo.sync_data_node_ec_shards(dn, hb["ec_shards"])
        # instant EC-shard deltas (master_grpc_server.go:83-98 incremental
        # branch): register/unregister only the changed shard bits
        for m in hb.get("new_ec_shards", []):
            self.topo.register_ec_shards(m["id"], dn, m.get("ec_index_bits", 0))
        for m in hb.get("deleted_ec_shards", []):
            self.topo.unregister_ec_shards(
                m["id"], dn, m.get("ec_index_bits", ~0)
            )
        return {"volume_size_limit": self.topo.volume_size_limit}

    def handle_node_disconnect(self, dn: DataNode) -> None:
        affected = self.topo.unregister_data_node(dn)
        for vid in affected:
            self._notify(vid, dn, deleted=True)

    # -- location push (KeepConnected) ---------------------------------------
    def subscribe(self, client_name: str, fn: LocationSubscriber) -> None:
        self._subscribers[client_name] = fn

    def unsubscribe(self, client_name: str) -> None:
        self._subscribers.pop(client_name, None)

    def _notify(self, vid: int, dn: DataNode, deleted: bool) -> None:
        # location-scoped, like the reference's VolumeLocation push:
        # deleted=True means "this url no longer serves vid", NOT that the
        # volume is gone — subscribers evict the (vid, url) pair only.
        event = {
            "vid": vid,
            "url": dn.url(),
            "public_url": dn.public_url or dn.url(),
            "deleted": deleted,
        }
        for fn in list(self._subscribers.values()):
            try:
                fn(event)
            except Exception:
                glog.exception("volume-location subscriber failed")
        with self._loc_cond:
            self._loc_version += 1
            self._loc_log.append((self._loc_version, event))
            self._loc_cond.notify_all()

    def location_snapshot(self) -> dict:
        """Full vid → [{url, public_url}] map from the current topology."""
        locs: dict[int, list[dict]] = {}
        with self._lock:
            for dn in self.topo.data_nodes():
                for vid in dn.volumes:
                    locs.setdefault(vid, []).append(
                        {"url": dn.url(), "public_url": dn.public_url or dn.url()}
                    )
        return {str(vid): v for vid, v in locs.items()}

    def location_deltas(self, since: int, timeout: float = 0.0) -> dict:
        """Events after version `since`; blocks up to `timeout` if none yet.

        If `since` predates the retained log window, returns a full snapshot
        instead (the caller must replace, not merge, its vid map) — the
        KeepConnected stream's reconnect-resends-everything behavior
        (master_grpc_server.go:99-120).
        """
        if since < 0:
            with self._loc_cond:
                version = self._loc_version
            return {"version": version, "snapshot": self.location_snapshot()}
        with self._loc_cond:
            if self._loc_version == since and timeout > 0:
                self._loc_cond.wait(timeout)
            oldest = self._loc_log[0][0] if self._loc_log else self._loc_version + 1
            if since + 1 < oldest and self._loc_version > since:
                return {
                    "version": self._loc_version,
                    "snapshot": self.location_snapshot(),
                }
            events = [e for v, e in self._loc_log if v > since]
            return {"version": self._loc_version, "events": events}

    # -- assignment (master_server_handlers.go:96-150) -----------------------
    def assign(
        self,
        count: int = 1,
        replication: str = "",
        collection: str = "",
        ttl: str = "",
        data_center: str = "",
        writable_volume_count: int = 0,
    ) -> AssignResult:
        rp = (
            ReplicaPlacement.from_string(replication)
            if replication
            else self.default_replication
        )
        ttl_obj = read_ttl(ttl) if ttl else EMPTY_TTL
        layout = self.topo.get_volume_layout(collection, rp, ttl_obj)
        option = VolumeGrowOption(
            collection=collection,
            replica_placement=rp,
            ttl=ttl_obj,
            data_center=data_center,
        )
        with self._lock:
            if layout.active_volume_count() == 0:
                grow = writable_volume_count or VolumeGrowth.default_grow_count(rp)
                self.vg.grow_by_count(self.topo, option, grow)
            try:
                vid, locations = layout.pick_for_write(data_center)
            except NoWritableVolumesError:
                grow = writable_volume_count or VolumeGrowth.default_grow_count(rp)
                self.vg.grow_by_count(self.topo, option, grow)
                vid, locations = layout.pick_for_write(data_center)
        key = self.sequencer.next_file_id(count)
        cookie = secrets.randbits(32)
        fid = str(FileId(vid, key, cookie))
        main = locations[0]
        return AssignResult(
            fid=fid,
            url=main.url(),
            public_url=main.public_url or main.url(),
            count=count,
            replicas=[dn.url() for dn in locations[1:]],
        )

    # -- lookup (master_server_handlers.go:32-60) ----------------------------
    def lookup_volume(self, vid: int, collection: str = "") -> list[dict]:
        locations = self.topo.lookup(collection, vid)
        if not locations:
            # EC volumes are located per shard
            by_shard = self.topo.lookup_ec_shards(vid)
            nodes = {dn.id: dn for locs in by_shard.values() for dn in locs}
            locations = list(nodes.values())
        return [{"url": dn.url(), "public_url": dn.public_url or dn.url()} for dn in locations]

    def lookup_ec_volume(self, vid: int) -> dict:
        by_shard = self.topo.lookup_ec_shards(vid)
        return {
            "volume_id": vid,
            "shard_id_locations": {
                sid: [dn.url() for dn in nodes] for sid, nodes in by_shard.items()
            },
        }

    # -- collections ---------------------------------------------------------
    def collection_list(self) -> list[str]:
        return self.topo.collection_names()

    def collection_delete(self, name: str) -> list[int]:
        return self.topo.delete_collection(name)

    # -- admin lock (master_grpc_server_admin.go:65-113) ---------------------
    def lease_admin_token(
        self, client_name: str, previous_token: Optional[str] = None
    ) -> str:
        with self._lock:
            now = time.time()
            expired = now - self._admin_lock_ts > 60
            if (
                self._admin_lock_token is None
                or expired
                or self._admin_lock_token == previous_token
            ):
                self._admin_lock_token = previous_token or secrets.token_hex(16)
                self._admin_lock_ts = now
                self._admin_lock_client = client_name
                return self._admin_lock_token
            raise RuntimeError(f"admin lock held by {self._admin_lock_client}")

    def release_admin_token(self, token: str) -> None:
        with self._lock:
            if self._admin_lock_token == token:
                self._admin_lock_token = None

    # -- vacuum orchestration (topology_vacuum.go:147) -----------------------
    def vacuum(
        self,
        check_garbage: Callable[[DataNode, int], float],
        compact: Callable[[DataNode, int], bool],
        garbage_threshold: Optional[float] = None,
    ) -> list[int]:
        """Scan all layouts; for each volume whose max replica garbage ratio
        exceeds the threshold, run compaction on every replica. The two
        callbacks abstract the volume-server RPCs. Returns compacted vids."""
        threshold = (
            self.garbage_threshold if garbage_threshold is None else garbage_threshold
        )
        compacted = []
        for layout in list(self.topo.layouts.values()):
            for vid, locations in list(layout.vid2location.items()):
                if not locations:
                    continue
                try:
                    ratio = max(check_garbage(dn, vid) for dn in locations)
                except Exception as e:
                    glog.V(2).info("vacuum check vid %s failed: %s", vid, e)
                    continue
                if ratio < threshold:
                    continue
                with layout._lock:
                    layout._remove_from_writable(vid)
                try:
                    ok = True
                    for dn in list(locations):
                        try:
                            ok = compact(dn, vid) and ok
                        except Exception:
                            ok = False  # unreachable replica: skip, keep scanning
                    if ok:
                        compacted.append(vid)
                finally:
                    with layout._lock:
                        layout._ensure_writable_state(vid)
        return compacted

    # -- cluster status ------------------------------------------------------
    def topology_info(self) -> dict:
        dcs = []
        for dc in self.topo.children.values():
            racks = []
            for rack in dc.children.values():
                nodes = [
                    {
                        "id": dn.id,
                        "url": dn.url(),
                        "volumes": len(dn.volumes),
                        "ec_shards": {
                            vid: bin(bits).count("1")
                            for vid, bits in dn.ec_shards.items()
                        },
                        "max": dn.max_volume_count(),
                    }
                    for dn in rack.children.values()
                    if isinstance(dn, DataNode)
                ]
                racks.append({"id": rack.id, "nodes": nodes})
            dcs.append({"id": dc.id, "racks": racks})
        return {
            "max_volume_id": self.topo.max_volume_id,
            "data_centers": dcs,
            "layouts": {
                f"{k[0] or '_'}/{k[1]}/{k[2] or '-'}": v.stats()
                for k, v in self.topo.layouts.items()
            },
        }
