"""VolumeGrowth: pick servers for new volumes honoring xyz replica placement.

Mirrors `weed/topology/volume_growth.go:113` (findEmptySlotsForOneVolume):
pick DiffDataCenterCount+1 data centers (weighted random, each must have
enough racks/slots), then DiffRackCount+1 racks in the main DC, then
SameRackCount+1 servers in the main rack, then one free server in each other
rack/DC. Allocation on the chosen servers goes through an injected
`allocate_volume` callback (gRPC in the daemon, in-process in tests).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..storage.replica_placement import ReplicaPlacement
from ..storage.ttl import TTL, EMPTY_TTL
from .topology import DataCenter, DataNode, NoFreeSpaceError, Rack, Topology


@dataclass
class VolumeGrowOption:
    collection: str = ""
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=lambda: EMPTY_TTL)
    preallocate: int = 0
    data_center: str = ""
    rack: str = ""
    data_node: str = ""


# allocate_volume(dn, vid, option) — raises on failure
AllocateVolumeFn = Callable[[DataNode, int, VolumeGrowOption], None]


def find_empty_slots_for_one_volume(
    topo: Topology, option: VolumeGrowOption
) -> list[DataNode]:
    rp = option.replica_placement

    def dc_filter(node) -> Optional[str]:
        if option.data_center and isinstance(node, DataCenter) and node.id != option.data_center:
            return f"not preferred dc {option.data_center}"
        if len(node.children) < rp.diff_rack_count + 1:
            return f"only {len(node.children)} racks"
        if node.free_space() < rp.diff_rack_count + rp.same_rack_count + 1:
            return f"free {node.free_space()} too low"
        possible_racks = 0
        for rack in node.children.values():
            free_nodes = sum(1 for n in rack.children.values() if n.free_space() >= 1)
            if free_nodes >= rp.same_rack_count + 1:
                possible_racks += 1
        if possible_racks < rp.diff_rack_count + 1:
            return f"only {possible_racks} usable racks"
        return None

    main_dc, other_dcs = topo.pick_nodes_by_weight(
        rp.diff_data_center_count + 1, dc_filter
    )

    def rack_filter(node) -> Optional[str]:
        if option.rack and isinstance(node, Rack) and node.id != option.rack:
            return f"not preferred rack {option.rack}"
        if node.free_space() < rp.same_rack_count + 1:
            return "not enough free slots"
        if len(node.children) < rp.same_rack_count + 1:
            return "not enough data nodes"
        free_nodes = sum(1 for n in node.children.values() if n.free_space() >= 1)
        if free_nodes < rp.same_rack_count + 1:
            return "not enough free data nodes"
        return None

    main_rack, other_racks = main_dc.pick_nodes_by_weight(
        rp.diff_rack_count + 1, rack_filter
    )

    def server_filter(node) -> Optional[str]:
        if option.data_node and node.is_data_node() and node.id != option.data_node:
            return f"not preferred node {option.data_node}"
        if node.free_space() < 1:
            return "no free slots"
        return None

    main_server, other_servers = main_rack.pick_nodes_by_weight(
        rp.same_rack_count + 1, server_filter
    )

    servers: list[DataNode] = [main_server]  # type: ignore[list-item]
    servers.extend(other_servers)  # type: ignore[arg-type]
    for rack in other_racks:
        servers.append(rack.reserve_one_volume())
    for dc in other_dcs:
        servers.append(dc.reserve_one_volume())
    return servers


class VolumeGrowth:
    def __init__(self, allocate_volume: AllocateVolumeFn, on_register=None):
        self.allocate_volume = allocate_volume
        # called (vid, DataNode) after each successful placement so the
        # master can push the new location to KeepConnected subscribers
        self.on_register = on_register

    def grow_by_count(
        self, topo: Topology, option: VolumeGrowOption, count: int = 1
    ) -> int:
        """Grow up to `count` volumes; returns how many were created
        (GrowByCountAndType, volume_growth.go:88). Partial growth is success
        — the error is re-raised only when nothing could be grown, matching
        the assign flow where any new writable volume unblocks the client."""
        grown = 0
        for _ in range(count):
            try:
                servers = find_empty_slots_for_one_volume(topo, option)
            except NoFreeSpaceError:
                if grown == 0:
                    raise
                break
            vid = topo.next_volume_id()
            self._grow_one(topo, vid, option, servers)
            grown += 1
        return grown

    def _grow_one(
        self,
        topo: Topology,
        vid: int,
        option: VolumeGrowOption,
        servers: list[DataNode],
    ) -> None:
        from .topology import VolumeInfo

        for server in servers:
            self.allocate_volume(server, vid, option)
            vi = VolumeInfo(
                id=vid,
                collection=option.collection,
                replica_placement=option.replica_placement,
                ttl=option.ttl,
                version=3,
            )
            # the heartbeat sync paths mutate server.volumes and the
            # layouts under topo._lock (an RLock) from the background
            # domain; growth runs on a handler thread, so it must take
            # the same lock or a full sync can interleave mid-register
            with topo._lock:
                server.volumes[vid] = vi
                topo._register_volume(vi, server)
            if self.on_register is not None:
                self.on_register(vid, server)

    @staticmethod
    def default_grow_count(rp: ReplicaPlacement) -> int:
        """How many volumes to grow per automatic growth
        (master_server_handlers.go / vg growth defaults by copy count)."""
        copy_count = rp.copy_count()
        if copy_count == 1:
            return 7
        if copy_count == 2:
            return 6
        if copy_count == 3:
            return 3
        return 1
