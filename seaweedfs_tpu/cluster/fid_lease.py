"""Lease-granted fid ranges: assign storms scale with the filer fleet.

Single-filer clusters coalesce per-request assigns (_AssignCoalescer),
but a FLEET of filers still serializes every write on the master's
``/dir/assign`` — one sequencer bump per round trip, N filers deep. The
fix mirrors the reference's batch-allocating sequencers (etcd/snowflake,
``weed/sequence``): the master leases a whole needle-key RANGE to a
filer in one round trip, and the filer mints fids locally until the
range runs dry or the lease expires.

Crash safety is the point of this module. A leased range is
indistinguishable from used ids — the filer may have minted any of them
before the master died — so a grant is durable BEFORE the response
leaves the master: fsync'd JSONL journal, replayed on restart into
``sequencer.set_max(end of every granted range)``. The invariant the
crash-replay test pins: across any master restart, no fid is ever
issued twice. (Unused tail of a granted range = needle-id gaps;
harmless, exactly like the reference's batch sequencers.)

Expiry is bookkeeping, not reclamation: an expired lease's unused keys
are never re-issued (they are burned into the journal); expiry exists so
the lease table stays bounded and ``/metrics`` can show live leases.

Filer side, :class:`LeasedFidSource` wraps the grant RPC: it mints
``FileId(vid, start+i, cookie)`` locally, re-leases when dry, and falls
back to the caller's per-request assign path on any error — including
auth-enforced clusters where this filer holds no signing key (master
tokens cover only the base fid; minted fids need self-signed JWTs, the
``_FidBatch`` discipline).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

from ..util import glog
from ..util.locks import make_lock
from ..util.racecheck import instrument


def lease_seconds() -> float:
    raw = os.environ.get("SWEED_FID_LEASE_S", "30").strip()
    try:
        v = float(raw)
    except ValueError:
        return 30.0
    return v if (v == v and v > 0) else 30.0


def lease_count() -> int:
    """Keys per grant. Modest by default: a dying filer burns at most
    this many ids, and one lease pins writes to one volume for at most
    this many needles before the next grant re-randomizes placement."""
    raw = os.environ.get("SWEED_FID_LEASE_COUNT", "128").strip()
    if not (raw.isascii() and raw.isdigit()) or int(raw) < 1:
        return 128
    return int(raw)


@instrument
class FidLeaseManager:
    """Master-side lease table + crash-safe grant/renew/expiry journal.

    The caller (master_server) reserves the key range through its normal
    assign path — volume pick + sequencer bump — then registers the
    range here; ``register`` journals it durably and only then may the
    response go on the wire."""

    def __init__(self, journal_path: Optional[str] = None):
        self._lock = make_lock("FidLeaseManager._lock")
        self._path = journal_path
        self._fh = None
        self._leases: dict[str, dict] = {}
        self._seq = 0
        self._granted = 0
        self._renewed = 0
        self._expired = 0
        self._replayed_max = 0

    # -- journal -------------------------------------------------------------
    def _append_locked(self, rec: dict) -> None:
        """Caller holds ``self._lock`` (the _locked convention)."""
        if not self._path:
            return
        if self._fh is None:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            self._fh = open(self._path, "a", encoding="utf-8")
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._fh.flush()
        # sweedlint: ok blocking-under-lock grant durability IS the serialization point: the journal append must be ordered with the table mutation, and a lease RPC happens once per SWEED_FID_LEASE_COUNT fids
        os.fsync(self._fh.fileno())

    def replay(self, set_max: Callable[[int], None]) -> int:
        """Restart path: push every journaled grant's range end into the
        sequencer BEFORE it issues anything. Torn last lines (crash mid-
        append) are skipped — a torn grant never answered its RPC, so no
        filer holds that range. Returns the highest key protected."""
        if not self._path or not os.path.exists(self._path):
            return 0
        high = 0
        with open(self._path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail; grant never acked
                if rec.get("op") == "grant":
                    end = int(rec.get("key", 0)) + int(rec.get("count", 0))
                    if end > high:
                        high = end
        if high:
            set_max(high)
            with self._lock:
                self._replayed_max = high
        return high

    # -- lease table ---------------------------------------------------------
    def register(self, client: str, vid: int, key: int, count: int,
                 ttl_s: Optional[float] = None) -> dict:
        """Durably record a reserved range as leased to ``client``.
        Returns {lease_id, expires}. MUST complete before the grant
        response is sent — the journal is what makes a restarted master
        honor ranges in flight."""
        ttl = ttl_s if ttl_s else lease_seconds()
        with self._lock:
            self._seq += 1
            lease_id = f"L{self._seq}-{key}"
            expires = time.time() + ttl
            rec = {
                "op": "grant", "lease_id": lease_id, "client": client,
                "vid": vid, "key": key, "count": count, "expires": expires,
            }
            self._append_locked(rec)
            self._leases[lease_id] = rec
            self._granted += 1
        return {"lease_id": lease_id, "expires": expires}

    def renew(self, lease_id: str, ttl_s: Optional[float] = None
              ) -> Optional[float]:
        """Extend a live lease; None for unknown/expired ids (the filer
        then grants afresh — renewal is an optimization, never required
        for correctness)."""
        ttl = ttl_s if ttl_s else lease_seconds()
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease["expires"] <= time.time():
                return None
            lease["expires"] = time.time() + ttl
            self._append_locked({"op": "renew", "lease_id": lease_id,
                          "expires": lease["expires"]})
            self._renewed += 1
            return lease["expires"]

    def expire_stale(self) -> int:
        """Drop expired leases from the live table (their ranges stay
        burned — the grant journal already protects them)."""
        now = time.time()
        with self._lock:
            stale = [lid for lid, rec in self._leases.items()
                     if rec["expires"] <= now]
            for lid in stale:
                del self._leases[lid]
                self._append_locked({"op": "expire", "lease_id": lid})
            self._expired += len(stale)
        return len(stale)

    def stats(self) -> dict:
        with self._lock:
            now = time.time()
            return {
                "live": sum(1 for r in self._leases.values()
                            if r["expires"] > now),
                "granted": self._granted,
                "renewed": self._renewed,
                "expired": self._expired,
                "replayed_max_key": self._replayed_max,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class LeasedFidSource:
    """Filer-side minting over granted ranges, one range per
    (collection, replication, ttl) key.

    ``grant_fn(collection, replication, ttl, count)`` performs the lease
    RPC and returns the master's response dict; ``fallback_fn`` is the
    per-request assign path used when leasing can't serve (RPC failure,
    auth without a local signing key, disabled). ``sign_fn(fid)`` mints
    the per-fid JWT on auth clusters ('' when unsigned)."""

    def __init__(self, grant_fn, fallback_fn,
                 sign_fn: Optional[Callable[[str], str]] = None):
        self._grant = grant_fn
        self._fallback = fallback_fn
        self._sign = sign_fn
        self._lock = make_lock("LeasedFidSource._lock")
        self._ranges: dict[tuple, dict] = {}
        self.minted = 0
        self.leases = 0
        self.fallbacks = 0

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("SWEED_FID_LEASE", "1").strip() != "0"

    def assign(self, collection: str, replication: str, ttl: str):
        from .. import operation
        from ..storage.file_id import FileId

        if not self.enabled():
            return self._fallback(collection, replication, ttl)
        key = (collection, replication, ttl)
        with self._lock:
            rng = self._ranges.get(key)
            if (rng is None or rng["next"] >= rng["end"]
                    or rng["expires"] <= time.time()):
                rng = self._lease_locked(key)
                if rng is None:
                    self.fallbacks += 1
                else:
                    self._ranges[key] = rng
            if rng is not None:
                i = rng["next"]
                rng["next"] += 1
                fid = str(FileId(rng["vid"], i, rng["cookie"]))
                auth = ""
                if rng["auth"]:
                    auth = (rng["base_auth"] if i == rng["base_key"]
                            else self._sign(fid))
                self.minted += 1
                return operation.Assignment(
                    fid=fid, url=rng["url"], public_url=rng["public_url"],
                    count=1, auth=auth,
                )
        # lease path unavailable: per-request assign outside the lock
        return self._fallback(collection, replication, ttl)

    def _lease_locked(self, key: tuple) -> Optional[dict]:
        """Caller holds ``self._lock`` (the _locked convention)."""
        collection, replication, ttl = key
        try:
            r = self._grant(collection, replication, ttl, lease_count())
        except Exception as e:  # lease is an optimization; any failure falls back to per-request assigns
            glog.V(1).info("fid lease grant failed (%s); falling back", e)
            return None
        if not r or r.get("error"):
            return None
        auth = r.get("auth", "")
        if auth and self._sign is None:
            # auth-enforced cluster, no local signing key: minted fids
            # beyond the base would be unusable — lease can't serve
            return None
        from ..storage.file_id import FileId

        try:
            base = FileId.parse(r["fid"])
        except (KeyError, ValueError):
            return None
        count = int(r.get("count", 1))
        self.leases += 1
        return {
            "vid": base.volume_id,
            "base_key": base.key,
            "next": base.key,
            "end": base.key + max(1, count),
            "cookie": base.cookie,
            "url": r["url"],
            "public_url": r.get("publicUrl", r["url"]),
            "auth": auth,
            "base_auth": auth,
            "expires": float(r.get("expires", time.time() + lease_seconds())),
            "lease_id": r.get("lease_id", ""),
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "minted": self.minted,
                "leases": self.leases,
                "fallbacks": self.fallbacks,
                "active_ranges": len(self._ranges),
            }
