"""Cluster plane: topology tree, volume layout/growth, sequencer, master.

Python reimplementation of `weed/topology` + `weed/sequence` + the master's
logic from `weed/server/master_*.go`, transport-agnostic: the master core
operates on plain dicts/objects so it can be driven in-process (tests mirror
the reference's JSON-fixture topology tests) or wrapped by HTTP/gRPC servers.
"""
