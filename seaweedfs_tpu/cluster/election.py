"""Master leader election + replicated sequence checkpoint.

Reference: `weed/server/raft_server.go:21-54` — the reference runs a raft
group among masters whose replicated state machine holds ONLY the sequence
counter (max file key); topology is rebuilt from volume-server heartbeats,
and non-leader masters proxy client traffic to the leader
(`master_server.go` proxyToLeader).

This build keeps those semantics with a lease-based protocol over the
masters' HTTP plane (no external coordination service, like the reference
which embeds its consensus):

- every master pings its peers; the smallest-url *alive* master claims
  leadership and sends `leader_beat`s carrying (term, max_file_key)
- followers accept beats from a leader with term ≥ their own and
  checkpoint the sequence high-water mark from each beat, so a failover
  never re-issues needle ids (the raft-snapshot-of-sequence analog)
- a follower that misses beats for `lease_seconds` re-evaluates; if it is
  now the smallest alive url it takes over with term+1
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..server.http_util import http_json


class LeaderElection:
    def __init__(
        self,
        self_url: str,
        peers: list[str],
        lease_seconds: float = 3.0,
        get_max_file_key: Optional[Callable[[], int]] = None,
        on_checkpoint: Optional[Callable[[int], None]] = None,
        on_leader_change: Optional[Callable[[str], None]] = None,
    ):
        self.self_url = self_url
        # peer set always includes self, deduplicated, stable order
        self.peers = sorted(set(peers) | {self_url})
        self.lease_seconds = lease_seconds
        self.get_max_file_key = get_max_file_key or (lambda: 0)
        self.on_checkpoint = on_checkpoint or (lambda k: None)
        self.on_leader_change = on_leader_change or (lambda u: None)

        self.term = 0
        self.leader: Optional[str] = None
        # grace: a freshly (re)started master must listen for one full lease
        # before claiming, or a restarted ex-leader with a cold sequencer
        # would depose the incumbent and re-issue ids
        self._last_beat = time.time()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self.leader == self.self_url

    # -- beat intake (follower side) -----------------------------------------
    def receive_beat(self, leader: str, term: int, max_file_key: int) -> dict:
        with self._lock:
            if term < self.term:
                return {"ok": False, "term": self.term}
            if (
                term == self.term
                and self.leader is not None
                and leader != self.leader
                and leader >= self.leader
            ):
                # equal-term split claim: smallest url wins deterministically
                return {"ok": False, "term": self.term}
            changed = leader != self.leader
            self.term = term
            self.leader = leader
            self._last_beat = time.time()
        if max_file_key:
            self.on_checkpoint(max_file_key)
        if changed:
            self.on_leader_change(leader)
        return {"ok": True, "term": term}

    # -- the election loop ---------------------------------------------------
    def start(self) -> "LeaderElection":
        if len(self.peers) == 1:
            # single master: it IS the cluster — lead immediately, no loop
            # latency (the reference's one-node raft elects itself at boot)
            self.term = 1
            self.leader = self.self_url
            self._last_beat = time.time()
            self.on_leader_change(self.self_url)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _alive_peers(self) -> list[str]:
        alive = [self.self_url]
        for p in self.peers:
            if p == self.self_url:
                continue
            try:
                r = http_json("GET", f"http://{p}/cluster/ping", timeout=1.0)
                if r.get("ok"):
                    alive.append(p)
            except Exception:
                continue
        return sorted(alive)

    def _send_beats(self) -> None:
        body = {
            "leader": self.self_url,
            "term": self.term,
            "max_file_key": self.get_max_file_key(),
        }
        for p in self.peers:
            if p == self.self_url:
                continue
            try:
                r = http_json(
                    "POST", f"http://{p}/cluster/leader_beat", body, timeout=1.0
                )
                rt = r.get("term", 0)
                if not r.get("ok") and (
                    rt > self.term or (rt == self.term and p < self.self_url)
                ):
                    # a higher term exists, or an equal-term claimant with a
                    # smaller url: step down and re-evaluate
                    with self._lock:
                        self.term = max(self.term, rt)
                        self.leader = None
                    return
            except Exception:
                continue

    def _loop(self) -> None:
        interval = self.lease_seconds / 3.0
        while not self._stop.wait(interval):
            if self.is_leader:
                self._send_beats()
                with self._lock:
                    self._last_beat = time.time()
                continue
            with self._lock:
                lease_fresh = (time.time() - self._last_beat) < self.lease_seconds
            if lease_fresh:
                continue
            # lease expired (or never had a leader): claim if smallest alive
            alive = self._alive_peers()
            if alive[0] == self.self_url:
                with self._lock:
                    self.term += 1
                    changed = self.leader != self.self_url
                    self.leader = self.self_url
                    self._last_beat = time.time()
                if changed:
                    self.on_leader_change(self.self_url)
                self._send_beats()
