"""Master leader election: majority-vote terms + quorum-gated leadership.

Reference: `weed/server/raft_server.go:21-54` — the reference embeds a raft
group among masters whose replicated state machine holds ONLY the sequence
counter (max file key); topology is rebuilt from volume-server heartbeats,
and non-leader masters proxy client traffic to the leader
(`master_server.go` proxyToLeader).

This build implements the same safety contract with a compact raft-shaped
protocol over the masters' HTTP plane (no external coordination service,
matching the reference's embedded consensus):

- **terms + one vote per term**: a candidate claims leadership only after
  collecting votes from a MAJORITY of the configured peer set; two leaders
  in one term are impossible, and two sides of a partition cannot both
  reach majority.
- **quorum-gated leading**: the leader counts beat acks every round and
  steps down (stops serving assigns) when it cannot reach a majority for a
  full lease — an isolated ex-leader goes silent instead of split-braining.
- **pre-vote phase**: a candidate first asks peers whether they WOULD vote
  (no state change on either side) and only bumps its real term after a
  pre-vote majority — so a flapping node never inflates the cluster term
  and cannot depose a healthy leader on heal (raft's pre-vote extension).
- **persisted term/vote**: with a `state_path`, (term, voted_for) survive
  restarts, so a bounced master cannot vote twice in one term (raft's
  durable currentTerm/votedFor). Without a state_path the startup lease
  grace makes double-voting unlikely but not impossible — pass a path in
  production.
- **state checkpoint riding beats**: each beat carries the sequence
  high-water mark AND the max volume id; followers checkpoint both, so a
  failover never re-issues needle ids or volume ids (the raft
  snapshot-of-sequence analog, plus the volume-id replication the
  reference gets from `Topology.NextVolumeId` going through raft).
- **up-to-date check**: a vote is denied to a candidate whose sequence
  checkpoint is behind the voter's, so a restarted master with a cold
  sequencer cannot win until it has caught up from beats.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from ..server.http_util import http_json
from ..util import glog
from ..util.locks import make_lock


class LeaderElection:
    def __init__(
        self,
        self_url: str,
        peers: list[str],
        lease_seconds: float = 3.0,
        get_max_file_key: Optional[Callable[[], int]] = None,
        on_checkpoint: Optional[Callable[[int], None]] = None,
        on_leader_change: Optional[Callable[[str], None]] = None,
        get_max_volume_id: Optional[Callable[[], int]] = None,
        on_volume_id_checkpoint: Optional[Callable[[int], None]] = None,
        state_path: Optional[str] = None,
    ):
        self.self_url = self_url
        # peer set always includes self, deduplicated, stable order
        self.peers = sorted(set(peers) | {self_url})
        self.lease_seconds = lease_seconds
        self.get_max_file_key = get_max_file_key or (lambda: 0)
        self.on_checkpoint = on_checkpoint or (lambda k: None)
        self.on_leader_change = on_leader_change or (lambda u: None)
        self.get_max_volume_id = get_max_volume_id or (lambda: 0)
        self.on_volume_id_checkpoint = on_volume_id_checkpoint or (lambda v: None)

        self.state_path = state_path
        self.term = 0
        self.voted_for: Optional[str] = None  # vote cast in self.term
        self.leader: Optional[str] = None
        if state_path and os.path.exists(state_path):
            try:
                with open(state_path) as f:
                    st = json.load(f)
                self.term = int(st.get("term", 0))
                self.voted_for = st.get("voted_for") or None
            except Exception:
                glog.warning("unreadable election state %s; starting cold",
                             state_path)
        # grace: a freshly (re)started master must listen for one full lease
        # before campaigning, or a restarted ex-leader with a cold sequencer
        # would disrupt the incumbent
        self._last_beat = time.time()
        self._last_quorum = 0.0  # leader side: last majority contact
        self._lock = make_lock("LeaderElection._lock")
        # Durable-state writer: serializes the (term, voted_for) disk
        # writes OUTSIDE self._lock so an fsync never blocks vote/beat
        # intake.  Never nested inside self._lock.
        self._persist_lock = make_lock("LeaderElection._persist_lock")
        self._persist_seq = 0  # bumped under self._lock at each snapshot
        self._persisted_seq = 0  # highest seq on disk; under _persist_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def quorum(self) -> int:
        return len(self.peers) // 2 + 1

    @property
    def is_leader(self) -> bool:
        """True only while leadership is quorum-backed: an isolated leader
        whose beats stopped reaching a majority reports False (and the
        master refuses assigns) even before it formally steps down."""
        if self.leader != self.self_url:  # sweedlint: ok lock-discipline lock-free probe; a stale read flips on the next beat round
            return False
        if len(self.peers) == 1:
            return True
        # sweedlint: ok lock-discipline staleness is exactly what the lease check bounds
        return (time.time() - self._last_quorum) < self.lease_seconds

    # -- vote intake ---------------------------------------------------------
    def _snapshot_locked(self) -> tuple[int, int, Optional[str]]:
        """Capture (seq, term, voted_for) for a durable write.  Called with
        self._lock held; the disk write happens later, in
        ``_persist_snapshot``, after the lock is released."""
        self._persist_seq += 1
        return (self._persist_seq, self.term, self.voted_for)

    def _persist_snapshot(
        self, snap: Optional[tuple[int, int, Optional[str]]]
    ) -> None:
        """Durable (term, voted_for) — must hit disk before the reply or
        request that references it leaves, or a restart could double-vote
        (raft's currentTerm/votedFor persistence).  Runs OUTSIDE
        self._lock so the fsync never stalls vote/beat intake; the
        sequence number makes concurrent writers safe — a slow older
        write is skipped rather than clobbering a newer one."""
        if snap is None or not self.state_path:
            return
        seq, term, voted_for = snap
        with self._persist_lock:
            if seq <= self._persisted_seq:
                return  # a newer snapshot already reached disk
            tmp = self.state_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"term": term, "voted_for": voted_for}, f)
                f.flush()
                # sweedlint: ok blocking-under-lock dedicated IO lock held only around this write, never nested in _lock
                os.fsync(f.fileno())
            os.replace(tmp, self.state_path)
            self._persisted_seq = seq

    def _up_to_date(self, max_file_key: int, max_volume_id: int) -> bool:
        """Candidate state must not be behind the voter's: a cold-restarted
        master with a stale sequence or volume-id counter cannot win."""
        return (
            max_file_key >= self.get_max_file_key()
            and max_volume_id >= self.get_max_volume_id()
        )

    def receive_vote_request(
        self,
        candidate: str,
        term: int,
        max_file_key: int,
        max_volume_id: int = 0,
        prevote: bool = False,
    ) -> dict:
        # The finally-persist runs after the lock is released and before
        # the return value actually leaves, so every reply that reflects
        # a term/vote mutation is durable first — without holding other
        # vote/beat intake hostage to the fsync.
        snap = None
        try:
            with self._lock:
                lease_fresh = (time.time() - self._last_beat) < self.lease_seconds
                disruptive = (
                    lease_fresh
                    and self.leader is not None
                    and self.leader != candidate
                )
                if prevote:
                    # answer only — NO state change on either side: the
                    # candidate bumps its real term only after a pre-vote
                    # majority, so a flapping node can't inflate cluster terms
                    granted = (
                        term > self.term
                        and not disruptive
                        and self._up_to_date(max_file_key, max_volume_id)
                    )
                    return {"granted": granted, "term": self.term}
                if term < self.term:
                    return {"granted": False, "term": self.term}
                if disruptive:
                    # deny without adopting the term: a live leader's followers
                    # don't let an out-of-band campaigner move the term forward
                    return {"granted": False, "term": self.term}
                if term > self.term:
                    stepping_down = self.leader == self.self_url
                    self.term = term
                    self.voted_for = None
                    self.leader = None
                    snap = self._snapshot_locked()
                    if stepping_down:
                        glog.info("%s: saw term %d, stepping down", self.self_url, term)
                if self.voted_for not in (None, candidate):
                    return {"granted": False, "term": self.term}
                if not self._up_to_date(max_file_key, max_volume_id):
                    return {"granted": False, "term": self.term}
                if self.voted_for != candidate:
                    self.voted_for = candidate
                    snap = self._snapshot_locked()
                self._last_beat = time.time()  # defer our own candidacy
                return {"granted": True, "term": self.term}
        finally:
            self._persist_snapshot(snap)

    # -- beat intake (follower side) -----------------------------------------
    def receive_beat(
        self,
        leader: str,
        term: int,
        max_file_key: int,
        max_volume_id: int = 0,
    ) -> dict:
        snap = None
        with self._lock:
            if term < self.term:
                return {"ok": False, "term": self.term}
            if term == self.term and self.leader not in (None, leader):
                # cannot happen with vote safety; guard anyway
                return {"ok": False, "term": self.term}
            changed = leader != self.leader
            term_changed = term != self.term
            self.term = term
            if term_changed:
                self.voted_for = None
            self.leader = leader
            self._last_beat = time.time()
            if term_changed:
                snap = self._snapshot_locked()
        self._persist_snapshot(snap)
        if max_file_key:
            self.on_checkpoint(max_file_key)
        if max_volume_id:
            self.on_volume_id_checkpoint(max_volume_id)
        if changed:
            self.on_leader_change(leader)
        return {"ok": True, "term": term}

    # -- the election loop ---------------------------------------------------
    def start(self) -> "LeaderElection":
        if len(self.peers) == 1:
            # single master: it IS the cluster — lead immediately, no loop
            # latency (the reference's one-node raft elects itself at boot)
            with self._lock:
                self.term = 1
                self.leader = self.self_url
                self._last_beat = time.time()
            self.on_leader_change(self.self_url)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _rpc(self, peer: str, path: str, body: dict) -> dict:
        """Send one control-plane message to a peer master. Overridable in
        tests to simulate partitions without sockets."""
        return http_json("POST", f"http://{peer}{path}", body, timeout=1.0)

    def _send_beats(self) -> int:
        """One beat round. Returns ack count including self; steps down
        inline when a peer reports a higher term."""
        body = {
            "leader": self.self_url,
            "term": self.term,  # sweedlint: ok lock-discipline stale term in a beat is rejected by peers and triggers step-down
            "max_file_key": self.get_max_file_key(),
            "max_volume_id": self.get_max_volume_id(),
        }
        acks = 1  # self
        for p in self.peers:
            if p == self.self_url:
                continue
            try:
                r = self._rpc(p, "/cluster/leader_beat", body)
            except Exception as e:
                glog.V(2).info("leader_beat to %s failed: %s", p, e)
                continue
            if r.get("ok"):
                acks += 1
            elif r.get("term", 0) > self.term:  # sweedlint: ok lock-discipline optimistic check; re-validated under the lock below
                snap = None
                with self._lock:
                    if r["term"] > self.term:
                        self.term = r["term"]
                        self.leader = None
                        self.voted_for = None
                        snap = self._snapshot_locked()
                self._persist_snapshot(snap)
                glog.info("%s: peer %s has term %d, stepping down",
                          self.self_url, p, r["term"])
                return 0
        return acks

    def _collect_votes(self, term: int, prevote: bool) -> Optional[int]:
        """One vote round; None means a higher term was seen (abort)."""
        body = {
            "candidate": self.self_url,
            "term": term,
            "max_file_key": self.get_max_file_key(),
            "max_volume_id": self.get_max_volume_id(),
            "prevote": prevote,
        }
        votes = 1  # self
        for p in self.peers:
            if p == self.self_url:
                continue
            try:
                r = self._rpc(p, "/cluster/vote", body)
            except Exception as e:
                glog.V(2).info("vote rpc to %s failed: %s", p, e)
                continue
            if r.get("granted"):
                votes += 1
            elif r.get("term", 0) > term:
                # adopt the observed (already-existing) cluster term so a
                # lagging candidate catches up and can campaign next round
                snap = None
                with self._lock:
                    if r["term"] > self.term:
                        self.term = r["term"]
                        self.voted_for = None
                        snap = self._snapshot_locked()
                self._persist_snapshot(snap)
                return None
        return votes

    def _campaign(self) -> None:
        """Pre-vote then real vote for term+1; lead only on a
        configured-set majority."""
        proposed = self.term + 1  # sweedlint: ok lock-discipline optimistic; re-validated under the lock before adopting
        pre = self._collect_votes(proposed, prevote=True)
        if pre is None or pre < self.quorum:
            glog.V(1).info("%s: pre-vote for term %d got %s/%d",
                           self.self_url, proposed, pre, self.quorum)
            return
        with self._lock:
            if self.term >= proposed:  # someone moved on meanwhile
                return
            self.term = proposed
            term = self.term
            self.voted_for = self.self_url
            snap = self._snapshot_locked()
        # durable before the first vote request leaves: a crash between
        # voting for self and soliciting peers must not forget the term
        self._persist_snapshot(snap)
        votes = self._collect_votes(term, prevote=False)
        if votes is None:
            return
        if votes < self.quorum:
            glog.V(1).info("%s: term %d campaign got %d/%d votes",
                           self.self_url, term, votes, self.quorum)
            return
        with self._lock:
            if self.term != term:  # someone moved on mid-campaign
                return
            self.leader = self.self_url
            self._last_beat = time.time()
            self._last_quorum = time.time()
        glog.info("%s: elected leader for term %d (%d/%d votes)",
                  self.self_url, term, votes, len(self.peers))
        self.on_leader_change(self.self_url)
        self._send_beats()

    def _rank(self) -> int:
        """Position of self among peers — staggers candidacy so the
        smallest url campaigns first and vote splits are rare (the
        deterministic stand-in for raft's randomized timeouts)."""
        return self.peers.index(self.self_url)

    def _loop(self) -> None:
        interval = self.lease_seconds / 3.0
        while not self._stop.wait(interval):
            with self._lock:
                leading = self.leader == self.self_url
            if leading:
                acks = self._send_beats()
                now = time.time()
                if acks >= self.quorum:
                    with self._lock:
                        self._last_quorum = now
                        self._last_beat = now
                # sweedlint: ok lock-discipline only this thread writes _last_quorum between beats
                elif now - self._last_quorum > self.lease_seconds:
                    with self._lock:
                        if self.leader == self.self_url:
                            self.leader = None
                    glog.info("%s: lost quorum, stepping down", self.self_url)
                continue
            with self._lock:
                expired_for = (time.time() - self._last_beat) - self.lease_seconds
            # stagger candidacy by rank to avoid split votes
            if expired_for < self._rank() * interval:
                continue
            self._campaign()
