"""Heat-driven lifecycle autopilot: the master's observe→plan→execute loop.

f4's thesis is that blob storage is a *lifecycle*: content is born hot
(triple-replicated, served from page cache), cools within weeks (erasure
coding recovers the replica overhead), and ends cold (bytes belong on the
cheapest tier that still answers reads). The reference leaves every one of
those transitions to an operator typing shell commands; this controller
closes the loop. It runs ONLY on the elected leader and each cycle:

* **observe** — walks the heartbeat-fresh topology: per-volume EWMA heat
  (stats/heat.py rides every beat), garbage ratio, replica set, EC shard
  map, remote-tier flag, and the scrub's corrupt needle/shard findings;
* **plan** — classifies each volume into a heat band
  (volume_layout.classify_heat) and emits a bounded action list, priority
  ordered: repair corruption first, then vacuum garbage, re-promote hot EC
  volumes, recall warming tiered volumes, EC cooling volumes, tier cold
  ones to the S3-class backend, and replica-boost hot plain volumes;
* **execute** — every action goes through the same staged-commit-protected
  paths the shell uses (fleet scheduler for EC, /admin/tier_* for the S3
  tier), so a daemon death mid-action leaves the volume fully in its old
  state or fully in its new one, never torn.

Safety interlocks, in the order they gate a cycle:

1. **pause switch** — ``lifecycle.pause`` flips an in-memory flag; the
   controller finishes nothing new until ``lifecycle.resume``.
2. **load interlock** — maintenance yields to traffic: when the admission
   controller's inflight gauge crosses a fraction of the serving watermark
   (server/http_util.py), the cycle defers. Re-checked before EVERY action,
   so a traffic spike mid-cycle stops the remaining moves.
3. **admin lease** — the controller leases the cluster admin lock around a
   cycle; a shell operator holding ``lock`` pauses the autopilot for free.
4. **plan journal** — an fsync'd single-document journal
   (``lifecycle_{port}.json`` next to the election state) records the plan
   before execution and every per-action state transition. A restarted or
   failed-over master replays it: actions that never started are abandoned
   (the next observation re-derives them if still warranted), actions
   caught mid-flight are re-validated against a FRESH observation and only
   re-executed when the volume still needs them — double-scheduling is
   structurally impossible because the predicate is current state, not the
   stale plan.
5. **budgets** — a global per-cycle action cap plus per-kind token budgets
   bound the blast radius of any single cycle; per-volume cooldown cycles
   stop flapping (a volume just EC'd cannot be un-EC'd next cycle).

Faultpoints (``lifecycle.journal.planned`` / ``.running`` / ``.done`` /
``.cycle`` / ``.recovered``) fire after each journal write so the chaos
matrix (tests/test_lifecycle_chaos.py) can kill the master at every
crash window and assert no torn tier state and no duplicated moves.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..util import glog
from ..util import faultpoints
from ..util.locks import make_lock
from ..util.parsers import tolerant_ufloat, tolerant_uint
from .volume_layout import classify_heat, heat_ceiling, heat_floor, tier_floor

from ..stats.metrics import default_registry as _registry

#: wall time of one observe→plan→execute cycle
CYCLE_HIST = _registry.histogram(
    "lifecycle_cycle_seconds",
    "lifecycle controller cycle latency (observe through journal close)",
)
#: per-action execution latency, labeled by action kind
ACTION_HIST = _registry.histogram(
    "lifecycle_action_seconds",
    "lifecycle action execution latency, by kind",
)

#: action kinds in planning priority order (repairs always first)
ACTION_KINDS = (
    "repair_shard",
    "repair_replica",
    "vacuum",
    "un_ec",
    "tier_down",
    "tier_up",
    "ec",
    "replica_boost",
)


@dataclass
class LifecycleConfig:
    """Knobs, all env-tunable so probes/chaos runs shrink the time scales."""

    interval: float = 30.0  # seconds between cycles
    cold_streak: int = 3  # consecutive cool/cold observations before EC/tier
    max_actions: int = 4  # global per-cycle concurrent-moves cap
    cooldown_cycles: int = 3  # per-volume quiet period after any action
    garbage_threshold: float = 0.3
    hot_replicas: int = 0  # replica-boost target; 0 disables boosting
    load_fraction: float = 0.5  # inflight ≥ fraction×watermark ⇒ defer
    tier_endpoint: str = ""  # S3 tier; empty ⇒ tiering disabled
    tier_bucket: str = "sweed-cold"
    tier_backend: str = ""
    budgets: dict = field(
        default_factory=lambda: {
            "repair_shard": 2,
            "repair_replica": 2,
            "vacuum": 2,
            "un_ec": 1,
            "tier_down": 2,
            "tier_up": 1,
            "ec": 2,
            "replica_boost": 1,
        }
    )

    @classmethod
    def from_env(cls) -> "LifecycleConfig":
        cfg = cls(
            interval=tolerant_ufloat(
                os.environ.get("SWEED_LIFECYCLE_INTERVAL", ""), 30.0
            )
            or 30.0,
            cold_streak=max(
                1,
                tolerant_uint(os.environ.get("SWEED_LIFECYCLE_COLD_STREAK"), 3),
            ),
            max_actions=max(
                1,
                tolerant_uint(os.environ.get("SWEED_LIFECYCLE_MAX_ACTIONS"), 4),
            ),
            cooldown_cycles=tolerant_uint(
                os.environ.get("SWEED_LIFECYCLE_COOLDOWN"), 3
            ),
            garbage_threshold=tolerant_ufloat(
                os.environ.get("SWEED_LIFECYCLE_GARBAGE", ""), 0.3
            ),
            hot_replicas=tolerant_uint(
                os.environ.get("SWEED_LIFECYCLE_HOT_REPLICAS"), 0
            ),
            load_fraction=tolerant_ufloat(
                os.environ.get("SWEED_LIFECYCLE_LOAD_FRACTION", ""), 0.5
            )
            or 0.5,
            tier_endpoint=os.environ.get("SWEED_TIER_ENDPOINT", ""),
            tier_bucket=os.environ.get("SWEED_TIER_BUCKET", "sweed-cold"),
            tier_backend=os.environ.get("SWEED_TIER_BACKEND", ""),
        )
        # "ec=4,vacuum=0" style per-kind token overrides
        for part in os.environ.get("SWEED_LIFECYCLE_BUDGETS", "").split(","):
            if "=" in part:
                kind, _, n = part.partition("=")
                if kind.strip() in cfg.budgets:
                    cfg.budgets[kind.strip()] = tolerant_uint(n.strip(), 0)
        return cfg


class LoadInterlock:
    """Maintenance yields to traffic: reads the admission controller's
    inflight gauge against the serving watermark (server/http_util.py).
    The controller consults this before the cycle AND before every action."""

    def __init__(self, fraction: float = 0.5):
        self.fraction = fraction
        self.last_reason = ""

    def maintenance_allowed(self) -> tuple[bool, str]:
        from ..server.http_util import SERVING, serving_watermark

        watermark = serving_watermark()
        limit = max(1, int(self.fraction * watermark))
        inflight = SERVING.inflight()
        if inflight >= limit:
            self.last_reason = (
                f"inflight {inflight} >= {limit} "
                f"({self.fraction:.0%} of watermark {watermark})"
            )
            return False, self.last_reason
        self.last_reason = ""
        return True, ""


def observe_topology(master_server) -> dict[int, dict]:
    """One observation pass over the master's heartbeat-fresh topology:
    vid → {kind, heat, band, garbage, replicas, tiered, corrupt, ...}.
    Pure read — defensive copies, no locks held across the return."""
    topo = master_server.master.topo
    obs: dict[int, dict] = {}
    for dn in topo.data_nodes():
        url = dn.url()
        for vid, vi in list(dn.volumes.items()):
            ob = obs.setdefault(
                vid,
                {
                    "vid": vid,
                    "collection": vi.collection,
                    "kind": "plain",
                    "heat": 0.0,
                    "garbage": 0.0,
                    "size": 0,
                    "replicas": [],
                    "tiered": False,
                    "read_only": False,
                    "corrupt_needles": {},
                    "ec_shards": {},
                    "corrupt_shards": {},
                },
            )
            ob["kind"] = "plain"  # a plain replica wins over shard leftovers
            ob["replicas"].append(url)
            ob["heat"] = max(ob["heat"], vi.read_heat + vi.write_heat)
            ob["size"] = max(ob["size"], vi.size)
            if vi.size > 0:
                ob["garbage"] = max(
                    ob["garbage"], vi.deleted_byte_count / vi.size
                )
            ob["tiered"] = ob["tiered"] or vi.remote_tier
            ob["read_only"] = ob["read_only"] or vi.read_only
            if vi.corrupt_needles:
                ob["corrupt_needles"][url] = vi.corrupt_needles
        for vid, bits in list(dn.ec_shards.items()):
            ob = obs.setdefault(
                vid,
                {
                    "vid": vid,
                    "collection": "",
                    "kind": "ec",
                    "heat": 0.0,
                    "garbage": 0.0,
                    "size": 0,
                    "replicas": [],
                    "tiered": False,
                    "read_only": False,
                    "corrupt_needles": {},
                    "ec_shards": {},
                    "corrupt_shards": {},
                },
            )
            ob["ec_shards"][url] = bits
            ob["heat"] = max(ob["heat"], dn.ec_read_heat.get(vid, 0.0))
            sids = dn.ec_corrupt.get(vid)
            if sids:
                ob["corrupt_shards"][url] = list(sids)
    for ob in obs.values():
        ob["band"] = classify_heat(ob["heat"])
    return obs


class ClusterOps:
    """Real executor: every action dogfoods the HTTP control plane the
    shell uses (the controller runs only on the leader, so ``master_url``
    is the local daemon). Each op is idempotent against current state —
    re-executing a completed action is a no-op or a cheap error."""

    def __init__(self, master_url: str, cfg: LifecycleConfig):
        self.master_url = master_url
        self.cfg = cfg
        self._env = None

    def _commands(self):
        from ..shell import commands as C

        if self._env is None:
            self._env = C.CommandEnv(self.master_url)
        return C, self._env

    def execute(self, action: dict, ob: dict) -> None:
        getattr(self, "_op_" + action["kind"])(action, ob)

    def _op_ec(self, action, ob) -> None:
        C, env = self._commands()
        C.ec_encode_fleet(env, [ob["vid"]], ob["collection"] or None)

    def _op_un_ec(self, action, ob) -> None:
        C, env = self._commands()
        C.ec_decode(env, ob["vid"], ob["collection"])

    def _op_vacuum(self, action, ob) -> None:
        from ..server.http_util import http_json

        for url in ob["replicas"]:
            r = http_json(
                "POST",
                f"http://{url}/admin/vacuum?volume={ob['vid']}",
            )
            if r.get("error"):
                raise RuntimeError(f"vacuum on {url}: {r['error']}")

    def _op_tier_up(self, action, ob) -> None:
        C, env = self._commands()
        if ob["kind"] == "ec":
            # demote-through-decode: a cold EC volume re-materializes as a
            # plain volume first, then its .dat moves to the S3 tier
            C.ec_decode(env, ob["vid"], ob["collection"])
        C.volume_tier_upload(
            env,
            ob["vid"],
            self.cfg.tier_endpoint,
            self.cfg.tier_bucket,
            keep_local=False,
            backend=self.cfg.tier_backend,
        )

    def _op_tier_down(self, action, ob) -> None:
        C, env = self._commands()
        C.volume_tier_download(env, ob["vid"])

    def _op_repair_shard(self, action, ob) -> None:
        from ..server.http_util import http_json

        C, env = self._commands()
        for url, sids in ob["corrupt_shards"].items():
            shards = ",".join(str(s) for s in sids)
            r = http_json(
                "POST",
                f"http://{url}/admin/ec/delete_shards?volume={ob['vid']}"
                f"&shards={shards}",
            )
            if r.get("error"):
                raise RuntimeError(f"drop corrupt shards on {url}: {r['error']}")
        C.ec_rebuild(env, ob["vid"], ob["collection"])

    def _op_repair_replica(self, action, ob) -> None:
        from ..server.http_util import http_json

        C, env = self._commands()
        healthy = [
            u for u in ob["replicas"] if u not in ob["corrupt_needles"]
        ]
        if not healthy:
            raise RuntimeError(
                f"volume {ob['vid']}: every replica reports corruption; "
                "needs a fleet rebuild from EC parity, not a re-fetch"
            )
        for url in ob["corrupt_needles"]:
            r = http_json(
                "POST",
                f"http://{url}/admin/delete_volume?volume={ob['vid']}",
            )
            if r.get("error"):
                raise RuntimeError(
                    f"drop corrupt replica on {url}: {r['error']}"
                )
            C.volume_copy(env, ob["vid"], target=url, source=healthy[0])

    def _op_replica_boost(self, action, ob) -> None:
        C, env = self._commands()
        holders = set(ob["replicas"])
        spare = [
            n["url"] for n in env.data_nodes() if n["url"] not in holders
        ]
        if not spare:
            raise RuntimeError(
                f"volume {ob['vid']}: no spare node for a replica boost"
            )
        C.volume_copy(env, ob["vid"], target=spare[0])


class LifecycleController:
    """The autopilot. Everything injectable for unit tests: ``observe``
    returns the vid→observation map, ``ops.execute(action, ob)`` performs
    one action, ``clock`` stamps the journal, ``is_leader`` gates cycles,
    ``lease``/``release`` wrap the master's admin lock."""

    def __init__(
        self,
        *,
        journal_path: Optional[str] = None,
        config: Optional[LifecycleConfig] = None,
        observe: Optional[Callable[[], dict]] = None,
        ops=None,
        is_leader: Callable[[], bool] = lambda: True,
        clock: Callable[[], float] = time.time,
        interlock: Optional[LoadInterlock] = None,
        lease: Optional[Callable[[str], str]] = None,
        release: Optional[Callable[[str], None]] = None,
    ):
        self.cfg = config or LifecycleConfig.from_env()
        self.journal_path = journal_path
        self._observe = observe or (lambda: {})
        self.ops = ops
        self._is_leader = is_leader
        self._clock = clock
        self.interlock = interlock or LoadInterlock(self.cfg.load_fraction)
        self._lease = lease
        self._release = release
        self._lock = make_lock("LifecycleController._lock")
        self._paused = False
        self._cycle = 0
        self._next_id = 1
        self._cold_streak: dict[int, int] = {}
        self._cooldown: dict[int, int] = {}  # vid → cycle it unlocks at
        self._resume_queue: list[dict] = []
        self._last_actions: list[dict] = []
        self._last_cycle_at = 0.0
        self._last_cycle_seconds = 0.0
        self.recovery: dict = {}
        self._recovered = False
        self._counters = {
            "cycles": 0,
            "actions_done": 0,
            "actions_failed": 0,
            "actions_deferred": 0,
            "cycles_deferred": 0,
            "cycles_skipped_locked": 0,
            "resumed": 0,
            "abandoned": 0,
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _register(self)

    # -- pause / resume -------------------------------------------------------
    def pause(self) -> None:
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    @property
    def paused(self) -> bool:
        with self._lock:
            return self._paused

    # -- plan journal ---------------------------------------------------------
    def _persist(self, doc: dict, fp: str) -> None:
        """Journal write + chaos window: the faultpoint fires AFTER the
        fsync'd rename, so an armed crash simulates dying with exactly
        this state durable."""
        if not self.journal_path:
            return
        from ..storage.commit import atomic_write

        atomic_write(
            self.journal_path,
            json.dumps(doc, sort_keys=True).encode(),
        )
        faultpoints.fire(fp, self.journal_path)

    def _load_journal(self) -> Optional[dict]:
        if not self.journal_path or not os.path.exists(self.journal_path):
            return None
        try:
            with open(self.journal_path, "rb") as f:
                return json.loads(f.read())
        except (OSError, ValueError) as e:
            glog.warning("lifecycle: unreadable journal %s: %s",
                         self.journal_path, e)
            return None

    def _recover(self) -> None:
        """Leadership (re)gain: resolve any in-flight cycle the previous
        incarnation left in the journal. Planned-never-started actions are
        abandoned — the next observation re-derives them if still needed.
        Running actions go to the resume queue, where the next cycle
        re-validates them against a FRESH observation before re-executing
        (idempotent roll-forward; a completed action fails the predicate
        and becomes a no-op, so nothing is ever double-scheduled)."""
        self._recovered = True
        doc = self._load_journal()
        if not doc or doc.get("state") == "done":
            return
        running = [a for a in doc.get("actions", []) if a["state"] == "running"]
        abandoned = [
            a for a in doc.get("actions", []) if a["state"] == "planned"
        ]
        recovery = {
            "cycle": doc.get("cycle", 0),
            "resumed": len(running),
            "abandoned": len(abandoned),
            "at": self._clock(),
        }
        with self._lock:
            self._resume_queue = running
            self._cycle = max(self._cycle, doc.get("cycle", 0))
            self._counters["resumed"] += len(running)
            self._counters["abandoned"] += len(abandoned)
            self.recovery = recovery
        glog.info(
            "lifecycle: recovered journal cycle %d (%d resumed, %d abandoned)",
            doc.get("cycle", 0), len(running), len(abandoned),
        )
        self._persist(
            {
                "cycle": doc.get("cycle", 0),
                "state": "done",
                "recovered": recovery,
                "actions": doc.get("actions", []),
            },
            "lifecycle.journal.recovered",
        )

    # -- planning -------------------------------------------------------------
    def _still_needed(self, action: dict, obs: dict) -> bool:
        """Re-validate an action against the CURRENT observation. Gates
        both resumed actions and fresh ones at execution time — the
        predicate is present state, so replaying a journal (or a stale
        plan racing a completed move) cannot duplicate work."""
        ob = obs.get(action["vid"])
        if ob is None:
            return False
        kind = action["kind"]
        if kind == "ec":
            return ob["kind"] == "plain" and not ob["tiered"]
        if kind == "un_ec":
            return ob["kind"] == "ec"
        if kind == "tier_up":
            return not ob["tiered"] and bool(self.cfg.tier_endpoint)
        if kind == "tier_down":
            return ob["tiered"]
        if kind == "vacuum":
            return (
                ob["kind"] == "plain"
                and ob["garbage"] >= self.cfg.garbage_threshold
            )
        if kind == "repair_shard":
            return bool(ob["corrupt_shards"])
        if kind == "repair_replica":
            return bool(ob["corrupt_needles"]) and len(
                ob["corrupt_needles"]
            ) < len(ob["replicas"])
        if kind == "replica_boost":
            return (
                ob["kind"] == "plain"
                and 0 < len(ob["replicas"]) < self.cfg.hot_replicas
            )
        return False

    def _plan(self, obs: dict, cycle: int) -> list[dict]:
        actions: list[dict] = []
        budgets = dict(self.cfg.budgets)
        planned_vids: set[int] = set()

        def want(kind: str, ob: dict, detail: str = "") -> None:
            vid = ob["vid"]
            if len(actions) >= self.cfg.max_actions:
                return
            if budgets.get(kind, 0) <= 0:
                return
            if vid in planned_vids:
                return
            if self._cooldown.get(vid, 0) > cycle:
                return
            budgets[kind] -= 1
            planned_vids.add(vid)
            actions.append(
                {
                    "id": self._next_id,
                    "kind": kind,
                    "vid": vid,
                    "collection": ob["collection"],
                    "state": "planned",
                    "error": "",
                    "detail": detail,
                }
            )
            self._next_id += 1

        ordered = [obs[v] for v in sorted(obs)]
        # 1. corruption repairs outrank every tiering decision
        for ob in ordered:
            if ob["corrupt_shards"]:
                want(
                    "repair_shard",
                    ob,
                    f"shards {sorted(set().union(*map(set, ob['corrupt_shards'].values())))}",
                )
            elif ob["corrupt_needles"] and len(ob["corrupt_needles"]) < len(
                ob["replicas"]
            ):
                want(
                    "repair_replica",
                    ob,
                    f"corrupt on {sorted(ob['corrupt_needles'])}",
                )
        # 2. reclaim garbage before it rides an EC encode or a tier upload
        for ob in ordered:
            if (
                ob["kind"] == "plain"
                and not ob["tiered"]
                and ob["garbage"] >= self.cfg.garbage_threshold
            ):
                want("vacuum", ob, f"garbage {ob['garbage']:.2f}")
        # 3. hot EC volumes pay reconstruction tax on every read: un-EC
        for ob in ordered:
            if ob["kind"] == "ec" and ob["band"] == "hot":
                want("un_ec", ob, f"heat {ob['heat']:.2f}")
        # 4. tiered volumes that warmed back up come home
        for ob in ordered:
            if ob["tiered"] and ob["band"] != "cold":
                want("tier_down", ob, f"band {ob['band']}")
        # 5/6. cooling: cold → S3 tier (when configured), cool → fleet EC.
        # Both demand a streak of consecutive sub-floor observations so a
        # single quiet heartbeat can't trigger a move.
        for ob in ordered:
            streak = self._cold_streak.get(ob["vid"], 0)
            if streak < self.cfg.cold_streak or ob["size"] <= 0:
                continue
            if (
                ob["band"] == "cold"
                and self.cfg.tier_endpoint
                and not ob["tiered"]
            ):
                want("tier_up", ob, f"cold streak {streak}")
            elif (
                ob["band"] in ("cool", "cold")
                and ob["kind"] == "plain"
                and not ob["tiered"]
            ):
                want("ec", ob, f"band {ob['band']} streak {streak}")
        # 7. hot plain volumes spread load across an extra replica
        if self.cfg.hot_replicas > 0:
            for ob in ordered:
                if (
                    ob["kind"] == "plain"
                    and ob["band"] == "hot"
                    and 0 < len(ob["replicas"]) < self.cfg.hot_replicas
                ):
                    want("replica_boost", ob, f"heat {ob['heat']:.2f}")
        return actions

    def _update_streaks(self, obs: dict) -> None:
        for vid, ob in obs.items():
            if ob["band"] in ("cool", "cold"):
                self._cold_streak[vid] = self._cold_streak.get(vid, 0) + 1
            else:
                self._cold_streak[vid] = 0
        for vid in list(self._cold_streak):
            if vid not in obs:
                del self._cold_streak[vid]

    # -- the cycle ------------------------------------------------------------
    def tick(self) -> dict:
        """One synchronous observe→plan→execute cycle. Unit tests drive
        this directly with injected observe/ops/clock."""
        t0 = time.monotonic()
        with self._lock:
            self._cycle += 1
            self._counters["cycles"] += 1
            cycle = self._cycle
            paused = self._paused
        summary = {"cycle": cycle, "actions": [], "deferred": "", "locked": ""}
        if paused:
            summary["deferred"] = "paused"
            return summary
        allowed, reason = self.interlock.maintenance_allowed()
        if not allowed:
            # traffic peak: skip even the observation — heartbeats keep
            # the streak state fresh enough, and observing costs topology
            # lock acquisitions the serving path is competing for
            self._counters["cycles_deferred"] += 1
            summary["deferred"] = reason
            return summary
        obs = self._observe()
        self._update_streaks(obs)
        with self._lock:
            resume = [
                a for a in self._resume_queue if self._still_needed(a, obs)
            ]
            self._resume_queue = []
        for a in resume:
            a["state"] = "planned"
            a["detail"] = (a.get("detail") or "") + " [resumed]"
        actions = resume + self._plan(obs, cycle)
        if not actions:
            with self._lock:
                self._last_cycle_at = self._clock()
                self._last_cycle_seconds = time.monotonic() - t0
            return summary
        token = None
        if self._lease is not None:
            try:
                token = self._lease("lifecycle")
            except RuntimeError as e:
                # an operator's shell holds the admin lock: their cycle
                self._counters["cycles_skipped_locked"] += 1
                summary["locked"] = str(e)
                return summary
        doc = {
            "cycle": cycle,
            "state": "planned",
            "started": self._clock(),
            "actions": actions,
        }
        try:
            with CYCLE_HIST.time():
                self._persist(doc, "lifecycle.journal.planned")
                for a in actions:
                    allowed, reason = self.interlock.maintenance_allowed()
                    if not allowed:
                        a["state"] = "deferred"
                        a["error"] = reason
                        self._counters["actions_deferred"] += 1
                        continue
                    if not self._still_needed(a, obs):
                        a["state"] = "noop"
                        continue
                    a["state"] = "running"
                    self._persist(doc, "lifecycle.journal.running")
                    try:
                        with ACTION_HIST.time(kind=a["kind"]):
                            self.ops.execute(a, obs[a["vid"]])
                        a["state"] = "done"
                        self._counters["actions_done"] += 1
                        self._cooldown[a["vid"]] = (
                            cycle + self.cfg.cooldown_cycles
                        )
                        self._cold_streak[a["vid"]] = 0
                    except Exception as e:  # noqa: BLE001 - one action must not kill the cycle
                        a["state"] = "failed"
                        a["error"] = str(e)
                        self._counters["actions_failed"] += 1
                        glog.warning(
                            "lifecycle: %s volume %d failed: %s",
                            a["kind"], a["vid"], e,
                        )
                    self._persist(doc, "lifecycle.journal.done")
                doc["state"] = "done"
                self._persist(doc, "lifecycle.journal.cycle")
        finally:
            if token is not None and self._release is not None:
                self._release(token)
        with self._lock:
            self._last_actions = actions
            self._last_cycle_at = self._clock()
            self._last_cycle_seconds = time.monotonic() - t0
        summary["actions"] = actions
        return summary

    # -- daemon loop ----------------------------------------------------------
    def start(self) -> "LifecycleController":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="lifecycle-controller"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._is_leader():
                    if not self._recovered:
                        self._recover()
                    self.tick()
                else:
                    # leadership lost: force a journal replay on regain
                    self._recovered = False
            except Exception as e:  # noqa: BLE001 - the autopilot must outlive any cycle
                glog.warning("lifecycle cycle crashed: %s", e)
            self._stop.wait(self.cfg.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        _unregister(self)

    # -- introspection --------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "paused": self._paused,
                "cycle": self._cycle,
                "interval": self.cfg.interval,
                "counters": dict(self._counters),
                "recovery": dict(self.recovery),
                "last_cycle": {
                    "at": self._last_cycle_at,
                    "seconds": round(self._last_cycle_seconds, 6),
                    "actions": [dict(a) for a in self._last_actions],
                },
                "interlock": {
                    "fraction": self.interlock.fraction,
                    "blocked": bool(self.interlock.last_reason),
                    "last_reason": self.interlock.last_reason,
                },
                "tier": {
                    "enabled": bool(self.cfg.tier_endpoint),
                    "endpoint": self.cfg.tier_endpoint,
                    "bucket": self.cfg.tier_bucket,
                    "backend": self.cfg.tier_backend,
                },
                "thresholds": {
                    "heat_floor": heat_floor(),
                    "heat_ceiling": heat_ceiling(),
                    "tier_floor": tier_floor(),
                    "cold_streak": self.cfg.cold_streak,
                    "garbage": self.cfg.garbage_threshold,
                },
                "cycle_latency": CYCLE_HIST.summary(),
                "action_latency": {
                    k: ACTION_HIST.summary(kind=k)
                    for k in ACTION_KINDS
                    if ACTION_HIST.summary(kind=k).get("count")
                },
            }


# -- process-wide snapshot for the sweed_lifecycle_* gauges -------------------
# Mirrors cluster/fleet.py: metrics callbacks read a module snapshot so the
# registry never holds controllers alive past their master's stop().
_ACTIVE: list = []
_ACTIVE_LOCK = threading.Lock()


def _register(c: LifecycleController) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.append(c)


def _unregister(c: LifecycleController) -> None:
    with _ACTIVE_LOCK:
        if c in _ACTIVE:
            _ACTIVE.remove(c)


def lifecycle_stats() -> dict:
    """Aggregate controller counters across every live master in-process
    (tests run several); deployments see one controller per master."""
    with _ACTIVE_LOCK:
        active = list(_ACTIVE)
    agg = {
        "controllers": len(active),
        "paused": 0,
        "cycles": 0,
        "actions_done": 0,
        "actions_failed": 0,
        "actions_deferred": 0,
        "cycles_deferred": 0,
        "cycles_skipped_locked": 0,
        "resumed": 0,
        "abandoned": 0,
    }
    for c in active:
        st = c.status()
        if st["paused"]:
            agg["paused"] += 1
        for k in (
            "cycles",
            "actions_done",
            "actions_failed",
            "actions_deferred",
            "cycles_deferred",
            "cycles_skipped_locked",
            "resumed",
            "abandoned",
        ):
            agg[k] += st["counters"][k]
    return agg
