"""Needle-id sequencers (weed/sequence/sequence.go + memory_sequencer.go)."""

from __future__ import annotations

import threading
from ..util.locks import make_lock


class MemorySequencer:
    """Monotonic batch allocator; the master checkpoints state via raft/
    snapshot in the reference (raft_server.go:30) — here persistence hooks
    are the caller's (set_max on recovery)."""

    def __init__(self, start: int = 1):
        self._counter = max(1, start)
        self._lock = make_lock("MemorySequencer._lock")

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class EtcdSequencer:
    """Cluster-shared needle-id allocator over etcd, the reference's
    optional `-master.sequencer=etcd` (weed/sequence/etcd_sequencer.go:45):
    batches are reserved with a compare-and-swap on one counter key, so
    independent masters can allocate without the raft leader.

    SDK-gated like the kafka/pubsub queues: raises ImportError without the
    'etcd3' package (MemorySequencer + beat checkpoints are the default)."""

    KEY = "seaweedfs.master.sequence"
    BATCH = 1000  # ids reserved per CAS round-trip (etcd_sequencer.go:20)

    def __init__(self, endpoint: str = "127.0.0.1:2379"):
        try:
            import etcd3  # type: ignore
        except ImportError as e:
            raise ImportError(
                "EtcdSequencer needs the 'etcd3' package; the in-memory "
                "sequencer (with heartbeat checkpoints) is the default"
            ) from e
        host, _, port = endpoint.partition(":")
        self._c = etcd3.client(host=host, port=int(port or 2379))
        self._lock = make_lock("EtcdSequencer._lock")
        self._next = 0   # local cursor within the reserved batch
        self._ceiling = 0

    def _reserve(self, at_least: int, need: int = 0) -> None:
        while True:
            raw, _ = self._c.get(self.KEY)
            cur = int(raw) if raw else 1
            # a single assign may ask for more ids than one batch: reserve
            # enough that the whole request fits inside our CAS'd window,
            # or two masters would hand out overlapping ranges
            want = max(cur, at_least) + max(self.BATCH, need)
            ok = (
                self._c.transactions is not None
                and self._c.transaction(
                    compare=[self._c.transactions.value(self.KEY) == (raw or b"")]
                    if raw else [self._c.transactions.version(self.KEY) == 0],
                    success=[self._c.transactions.put(self.KEY, str(want))],
                    failure=[],
                )[0]
            )
            if ok:
                # sweedlint: ok lock-discipline called with self._lock held by next_file_id/set_max
                self._next, self._ceiling = max(cur, at_least), want
                return

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            if self._next + count > self._ceiling:
                self._reserve(self._next, need=count)
            start = self._next
            self._next += count
            return start

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen >= self._ceiling:
                self._reserve(seen + 1)
            elif seen >= self._next:
                self._next = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._next
