"""Needle-id sequencers (weed/sequence/sequence.go + memory_sequencer.go)."""

from __future__ import annotations

import threading


class MemorySequencer:
    """Monotonic batch allocator; the master checkpoints state via raft/
    snapshot in the reference (raft_server.go:30) — here persistence hooks
    are the caller's (set_max on recovery)."""

    def __init__(self, start: int = 1):
        self._counter = max(1, start)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter
