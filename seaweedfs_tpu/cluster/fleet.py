"""Fleet-scale EC job scheduler: the master fans encode/rebuild across
mesh-backed volume servers.

The reference drives erasure coding entirely from the shell: one operator
process walks the topology and POSTs ``/admin/ec/generate`` at one server
after another (``command_ec_encode.go``). That serializes the fleet behind a
single client and dies with it. Here the MASTER owns a small job scheduler:

* volume servers that booted with ``SWEED_MESH=1`` report their
  ``jax.distributed`` coordinates in every heartbeat (``mesh`` dict:
  coordinator address, process_id, num_processes, initialized) — the
  scheduler's membership view is exactly the heartbeat-fresh topology, so a
  dead member stops receiving jobs the moment the reaper would drop it;
* ``ec.encode -fleet`` (or any client) POSTs ``/ec/fleet/encode`` with a
  volume-id list and the scheduler fans ``/admin/ec/generate`` calls over a
  bounded worker pool — the HTTP fan-out is the control-plane analog of the
  sharded codec's ``dp`` axis (each server encodes its own volumes, the
  master only sequences);
* each generate response carries ``bytes``/``seconds`` so the scheduler
  keeps a per-member encode-GB/s ledger for ``/_status`` and the
  ``sweed_fleet_*`` gauges.

Every encode lands on a server that already holds the volume (locality —
the job moves bytes through the codec, never across the wire) and the
staged-commit manifest inside ``Store.ec_encode_volume`` makes a mid-job
member death leave that volume either fully plain or fully EC, never torn.

Locking discipline: job-state mutation happens under the scheduler lock;
every HTTP dispatch and every topology lookup happens OUTSIDE it (the
blocking-under-lock and collective-under-lock lint rules both gate this
file at zero).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..util import glog
from ..util.locks import make_condition, make_lock
from ..util.retry import RetryPolicy

# encode can stream many GB through the codec; rebuild pulls shards first
_JOB_TIMEOUT = 600.0


from ..stats.metrics import default_registry as _registry

#: EC job round-trip latency (dispatch to settle), labeled by job kind
JOB_HIST = _registry.histogram(
    "fleet_job_encode_seconds",
    "fleet EC job round-trip latency (dispatch to settle), by kind",
)


@dataclass
class EcJob:
    id: int
    kind: str  # "encode" | "rebuild"
    vid: int
    collection: str = ""
    server: str = ""  # chosen member (empty until dispatch)
    state: str = "scheduled"  # scheduled → running → done | failed
    error: str = ""
    shards: list = field(default_factory=list)
    bytes: int = 0
    seconds: float = 0.0
    created: float = field(default_factory=time.monotonic)
    # retry/preemption bookkeeping: dispatches consumed, members this job
    # must avoid (they failed or died mid-job), and a monotonic epoch that
    # fences stale settles — a worker still blocked on a dead member's HTTP
    # call must not clobber the job after preemption re-queued it elsewhere
    attempts: int = 0
    excluded: list = field(default_factory=list)
    dispatch_epoch: int = 0

    @property
    def gbps(self) -> float:
        if self.seconds <= 0:
            return 0.0
        return self.bytes / self.seconds / 1e9

    def info(self) -> dict:
        return {
            "id": self.id,
            "kind": self.kind,
            "volume": self.vid,
            "collection": self.collection,
            "server": self.server,
            "state": self.state,
            "error": self.error,
            "shards": list(self.shards),
            "bytes": self.bytes,
            "seconds": round(self.seconds, 6),
            "gbps": round(self.gbps, 4),
        }


class EcJobScheduler:
    """Bounded-worker fan-out of EC jobs over heartbeat-registered members.

    ``locate`` maps a volume id to the urls currently holding it (the
    master's in-memory topology — cheap, no HTTP). Workers are lazy: an
    idle master spawns no threads.
    """

    def __init__(
        self,
        locate: Callable[[int], list],
        workers: Optional[int] = None,
        max_attempts: Optional[int] = None,
        retry_backoff_s: float = 0.5,
    ):
        self._locate = locate
        self._lock = make_lock("EcJobScheduler._lock")
        self._jobs: dict[int, EcJob] = {}
        self._queue: "queue.Queue[int]" = queue.Queue()
        self._members: dict[str, dict] = {}  # url -> mesh dict from heartbeat
        self._member_stats: dict[str, dict] = {}
        self._threads: list[threading.Thread] = []
        self._nworkers = workers or int(
            os.environ.get("SWEED_FLEET_WORKERS", "4")
        )
        self._max_attempts = max_attempts or int(
            os.environ.get("SWEED_FLEET_MAX_ATTEMPTS", "3")
        )
        self._retry_policy = RetryPolicy(
            attempts=self._max_attempts, base_s=retry_backoff_s, cap_s=5.0
        )
        self._timers: list[threading.Timer] = []
        self._retries = 0
        self._preempted = 0
        self._stop = threading.Event()
        self._done = make_condition(self._lock)
        self._next_id = 1
        _register(self)

    # -- membership (fed by the master's heartbeat handler) -------------------
    def observe_member(self, url: str, mesh: Optional[dict]) -> None:
        with self._lock:
            if mesh is None:
                self._members.pop(url, None)
            else:
                self._members[url] = dict(mesh)

    def drop_member(self, url: str) -> None:
        """Reaper/leave hook: a dead node must stop influencing placement —
        and jobs RUNNING on it are preempted back to scheduled (attempts
        permitting) so they retry on a surviving member instead of eating
        the full dispatch timeout. The worker still blocked on the dead
        member's socket is fenced out by the dispatch epoch."""
        requeue: list[int] = []
        fail: list[tuple[int, int]] = []
        with self._lock:
            self._members.pop(url, None)
            for job in self._jobs.values():
                if job.state != "running" or job.server != url:
                    continue
                job.excluded.append(url)
                job.server = ""
                job.dispatch_epoch += 1
                self._preempted += 1
                if job.attempts >= self._max_attempts:
                    fail.append((job.id, job.dispatch_epoch))
                else:
                    job.state = "scheduled"
                    requeue.append(job.id)
        for jid in requeue:
            glog.warning("fleet: preempting job %d off dead member %s",
                         jid, url)
            self._queue.put(jid)
        for jid, epoch in fail:
            self._settle(jid, epoch=epoch,
                         error=f"{url} died; attempt cap reached")

    def members(self) -> dict[str, dict]:
        with self._lock:
            return {u: dict(m) for u, m in self._members.items()}

    # -- job intake -----------------------------------------------------------
    def submit(self, kind: str, vid: int, collection: str = "") -> int:
        if kind not in ("encode", "rebuild"):
            raise ValueError(f"unknown fleet job kind {kind!r}")
        with self._lock:
            jid = self._next_id
            self._next_id += 1
            self._jobs[jid] = EcJob(jid, kind, vid, collection)
            self._ensure_workers_locked()
        self._queue.put(jid)
        glog.V(1).info("fleet: scheduled %s volume %d as job %d", kind, vid, jid)
        return jid

    def job_info(self, jid: int) -> Optional[dict]:
        with self._lock:
            job = self._jobs.get(jid)
            return job.info() if job else None

    def wait(self, jids: list, timeout: float = _JOB_TIMEOUT) -> bool:
        """Block until every job settled (done/failed) or timeout."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                pending = [
                    j for j in jids
                    if self._jobs.get(j)
                    and self._jobs[j].state in ("scheduled", "running")
                ]
                if not pending:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._done.wait(min(remaining, 1.0))

    # -- workers --------------------------------------------------------------
    def _ensure_workers_locked(self) -> None:
        alive = [t for t in self._threads if t.is_alive()]
        self._threads = alive
        while len(self._threads) < self._nworkers:
            t = threading.Thread(target=self._worker, daemon=True,
                                 name="fleet-ec-worker")
            self._threads.append(t)
            t.start()

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                jid = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                self._run_job(jid)
            except Exception as e:  # noqa: BLE001 - a worker must survive
                glog.warning("fleet job %d crashed the worker: %s", jid, e)
                self._settle(jid, error=f"scheduler: {e}")

    def _pick_target(self, job: EcJob) -> Optional[str]:
        """Locality first (the volume's own holders), mesh members preferred
        among replicas — the fan-out analog of placing dp-slices on the
        processes that already hold the bytes."""
        try:
            holders = [
                (h["url"] if isinstance(h, dict) else h)
                for h in (self._locate(job.vid) or [])
            ]
        except Exception as e:  # topology lookup must not kill the job path
            glog.V(1).info("fleet: locate volume %d failed: %s", job.vid, e)
            holders = []
        excluded = set(job.excluded)
        if job.kind == "encode":
            holders = [u for u in holders if u not in excluded]
            if not holders:
                return None
            members = self.members()
            meshed = [u for u in holders if members.get(u, {}).get("initialized")]
            return (meshed or holders)[0]
        # rebuild: any live mesh member will pull what it needs; fall back
        # to the volume's own holders when nothing registered a mesh
        members = self.members()
        candidates = [u for u, m in members.items() if m.get("initialized")] \
            or list(members) or holders
        candidates = [u for u in candidates if u not in excluded]
        if not candidates:
            return None
        # spread rebuilds round-robin by job id
        return sorted(candidates)[job.id % len(candidates)]

    def _run_job(self, jid: int) -> None:
        with self._lock:
            job = self._jobs.get(jid)
            if job is None or job.state != "scheduled":
                return
            job.state = "running"
            job.attempts += 1
            epoch = job.dispatch_epoch
        target = self._pick_target(job)
        if target is None:
            self._settle(jid, epoch=epoch,
                         error=f"volume {job.vid} has no live holder")
            return
        with self._lock:
            if job.dispatch_epoch != epoch or job.state != "running":
                return  # preempted while we were choosing a target
            job.server = target
        path = "generate" if job.kind == "encode" else "rebuild"
        from ..server.http_util import http_json
        from ..stats import trace as _trace

        t0 = time.monotonic()
        try:
            # scheduler worker threads run detached from any request
            # context: root a fresh trace per dispatch so the member-side
            # /admin/ec/* span nests under it (header injected by the
            # pooled transport), and time the whole round-trip
            with _trace.start_span(
                f"ec_{job.kind}", service="fleet",
                vid=job.vid, member=target,
            ), JOB_HIST.time(kind=job.kind):
                r = http_json(
                    "POST",
                    f"http://{target}/admin/ec/{path}?volume={job.vid}"
                    f"&collection={job.collection}",
                    timeout=_JOB_TIMEOUT,
                )
        except Exception as e:
            # transport-level failure (member died, refused, timed out):
            # retry on a DIFFERENT member with backoff, attempts permitting
            self._retry_or_fail(jid, epoch, f"{target}: {e}")
            return
        if r.get("error"):
            # the member answered: an application error (missing volume,
            # codec failure) re-breaks identically elsewhere — fail fast
            self._settle(jid, epoch=epoch, error=f"{target}: {r['error']}")
            return
        self._settle(
            jid,
            epoch=epoch,
            shards=r.get("shards") or r.get("rebuilt_shards") or [],
            nbytes=int(r.get("bytes", 0)),
            seconds=float(r.get("seconds", 0.0)) or (time.monotonic() - t0),
        )

    def _retry_or_fail(self, jid: int, epoch: int, error: str) -> None:
        with self._lock:
            job = self._jobs.get(jid)
            if job is None or job.dispatch_epoch != epoch:
                return  # preemption already re-queued (or settled) this job
            if job.attempts >= self._max_attempts:
                pass  # fall through to the terminal settle below
            else:
                if job.server:
                    job.excluded.append(job.server)
                job.server = ""
                job.state = "scheduled"
                job.dispatch_epoch += 1
                self._retries += 1
                delay = self._retry_policy.delay(job.attempts - 1)
                t = threading.Timer(delay, self._queue.put, args=(jid,))
                t.daemon = True
                self._timers.append(t)
                self._timers = [x for x in self._timers if x.is_alive()]
                glog.warning(
                    "fleet job %d attempt %d failed (%s); retrying on "
                    "another member in %.2fs", jid, job.attempts, error, delay)
                t.start()
                return
        self._settle(jid, epoch=epoch,
                     error=f"{error} (attempt cap {self._max_attempts})")

    def _settle(self, jid: int, epoch: Optional[int] = None, error: str = "",
                shards: Optional[list] = None, nbytes: int = 0,
                seconds: float = 0.0) -> None:
        with self._lock:
            job = self._jobs.get(jid)
            if job is None:
                return
            if epoch is not None and job.dispatch_epoch != epoch:
                return  # stale: the job moved on (preempted/re-queued)
            job.state = "failed" if error else "done"
            job.error = error
            job.shards = shards or []
            job.bytes = nbytes
            job.seconds = seconds
            if job.server:
                st = self._member_stats.setdefault(
                    job.server,
                    {"jobs": 0, "failed": 0, "bytes": 0, "seconds": 0.0,
                     "gbps": 0.0},
                )
                st["jobs"] += 1
                if error:
                    st["failed"] += 1
                else:
                    st["bytes"] += nbytes
                    st["seconds"] += seconds
                    st["gbps"] = round(job.gbps, 4)
            self._done.notify_all()
        if error:
            glog.warning("fleet job %d (%s volume %d) failed: %s",
                         jid, job.kind, job.vid, error)
        else:
            glog.V(1).info("fleet job %d done: %s volume %d on %s (%.2f GB/s)",
                           jid, job.kind, job.vid, job.server, job.gbps)

    # -- introspection --------------------------------------------------------
    def stats(self, jobs_tail: int = 32) -> dict:
        with self._lock:
            by_state = {"scheduled": 0, "running": 0, "done": 0, "failed": 0}
            for job in self._jobs.values():
                by_state[job.state] = by_state.get(job.state, 0) + 1
            tail = sorted(self._jobs)[-jobs_tail:]
            return {
                "members": {u: dict(m) for u, m in self._members.items()},
                "member_stats": {
                    u: dict(s) for u, s in self._member_stats.items()
                },
                "jobs_scheduled": self._next_id - 1,
                "jobs_running": by_state["running"] + by_state["scheduled"],
                "jobs_done": by_state["done"],
                "jobs_failed": by_state["failed"],
                "jobs_retried": self._retries,
                "jobs_preempted": self._preempted,
                "jobs": [self._jobs[j].info() for j in tail],
                # dispatch round-trip quantiles from fleet_job_encode_seconds
                "job_latency": {
                    "encode": JOB_HIST.summary(kind="encode"),
                    "rebuild": JOB_HIST.summary(kind="rebuild"),
                },
            }

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            threads = list(self._threads)
            timers = list(self._timers)
        for t in timers:
            t.cancel()
        for t in threads:
            t.join(timeout=2.0)
        _unregister(self)


# -- process-wide snapshot for /metrics gauges --------------------------------
# Mirrors the ncache pattern: metrics callbacks read a module snapshot so the
# registry never holds object references that outlive a test's daemons.
_ACTIVE: list = []
_ACTIVE_LOCK = threading.Lock()


def _register(s: EcJobScheduler) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.append(s)


def _unregister(s: EcJobScheduler) -> None:
    with _ACTIVE_LOCK:
        if s in _ACTIVE:
            _ACTIVE.remove(s)


def fleet_stats() -> dict:
    """Aggregate scheduler counters across every live master in-process
    (tests run several); single-daemon deployments see one scheduler."""
    with _ACTIVE_LOCK:
        active = list(_ACTIVE)
    agg = {"schedulers": len(active), "members": 0, "jobs_scheduled": 0,
           "jobs_running": 0, "jobs_done": 0, "jobs_failed": 0,
           "jobs_retried": 0, "jobs_preempted": 0, "member_gbps": {}}
    for s in active:
        st = s.stats(jobs_tail=0)
        agg["members"] += len(st["members"])
        for k in ("jobs_scheduled", "jobs_running", "jobs_done", "jobs_failed",
                  "jobs_retried", "jobs_preempted"):
            agg[k] += st[k]
        for u, ms in st["member_stats"].items():
            agg["member_gbps"][u] = ms.get("gbps", 0.0)
    return agg
