"""Loader for the native C++ kernel library (_sweed_native.so).

Builds lazily with g++ on first import if the shared object is missing or
older than the source, then exposes ctypes wrappers. All callers must
tolerate ImportError and fall back to pure-Python/numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sweed_native.cpp")
_SO = os.path.join(_DIR, "build", "_sweed_native.so")


def _ensure_built() -> str:
    if (not os.path.exists(_SO)) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        try:
            subprocess.run(
                ["make", "-C", _DIR, "-s"],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
            out = getattr(e, "stderr", b"") or b""
            raise ImportError(f"native build failed: {out.decode(errors='replace')}")
    return _SO


class _Lib:
    def __init__(self) -> None:
        self._c = ctypes.CDLL(_ensure_built())
        self._c.sweed_crc32c_update.restype = ctypes.c_uint32
        self._c.sweed_crc32c_update.argtypes = [
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        self._c.sweed_kernel_variant.restype = ctypes.c_char_p
        self._c.sweed_kernel_variant.argtypes = []
        self._c.sweed_rs_matmul.restype = None
        self._c.sweed_rs_matmul.argtypes = [
            ctypes.c_void_p,  # matrix
            ctypes.c_int,  # out_rows
            ctypes.c_int,  # k
            ctypes.c_size_t,  # n
            ctypes.c_void_p,  # in
            ctypes.c_void_p,  # out
        ]
        self._c.sweed_rs_prep_bytes.restype = ctypes.c_size_t
        self._c.sweed_rs_prep_bytes.argtypes = []
        self._c.sweed_rs_prep.restype = None
        self._c.sweed_rs_prep.argtypes = [
            ctypes.c_void_p,  # matrix
            ctypes.c_int,  # out_rows
            ctypes.c_int,  # k
            ctypes.c_void_p,  # prep out
        ]
        self._c.sweed_rs_matmul_prep.restype = None
        self._c.sweed_rs_matmul_prep.argtypes = [
            ctypes.c_void_p,  # prep
            ctypes.c_int,  # out_rows
            ctypes.c_int,  # k
            ctypes.c_size_t,  # n
            ctypes.c_void_p,  # in
            ctypes.c_void_p,  # out
        ]

    def crc32c_update(self, crc: int, data: bytes) -> int:
        return self._c.sweed_crc32c_update(crc, data, len(data))

    def kernel_variant(self) -> str:
        """Which rs_matmul path this build compiled in ('avx2'/'scalar')."""
        return self._c.sweed_kernel_variant().decode()

    def rs_prep(self, matrix: np.ndarray) -> np.ndarray:
        """Derive the kernel's per-coefficient multiply prep (GFNI affine
        qwords or PSHUFB nibble tables, depending on the build) for a whole
        matrix. Cache the returned blob per matrix and pass it back through
        ``rs_matmul(..., prep=blob)`` — the hot path then never touches the
        log/exp tables."""
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        out_rows, k = matrix.shape
        stride = self._c.sweed_rs_prep_bytes()
        prep = np.empty(out_rows * k * stride, dtype=np.uint8)
        self._c.sweed_rs_prep(matrix.ctypes.data, out_rows, k, prep.ctypes.data)
        return prep

    def rs_matmul(
        self,
        matrix: np.ndarray,
        data: np.ndarray,
        prep: "np.ndarray | None" = None,
        out: "np.ndarray | None" = None,
    ) -> np.ndarray:
        """(out_rows×k GF matrix) @ (k×n bytes) → (out_rows×n bytes).

        ``out`` reuses a caller-owned result buffer: a fresh np.empty of
        hundreds of MB is mmap'd, first-touch page-faulted, and returned to
        the OS on free — measured ~2× the kernel's own runtime at GFNI
        rates. Streaming callers that consume the parity before the next
        call should allocate once and pass it back in.
        """
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        out_rows, k = matrix.shape
        k2, n = data.shape
        if k != k2:
            raise ValueError(f"matrix k={k} != data rows {k2}")
        if out is None:
            out = np.empty((out_rows, n), dtype=np.uint8)
        elif (
            out.shape != (out_rows, n)
            or out.dtype != np.uint8
            or not out.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                f"out must be C-contiguous uint8 {(out_rows, n)}, "
                f"got {out.dtype} {out.shape}"
            )
        if prep is not None:
            self._c.sweed_rs_matmul_prep(
                prep.ctypes.data, out_rows, k, n,
                data.ctypes.data, out.ctypes.data,
            )
        else:
            self._c.sweed_rs_matmul(
                matrix.ctypes.data, out_rows, k, n,
                data.ctypes.data, out.ctypes.data,
            )
        return out


lib = _Lib()
