// turbo.cpp — native HTTP data plane ("turbo engine") for the volume server.
//
// The reference serves its small-file data plane from compiled Go
// (weed/server/volume_server_handlers_read.go:28,
//  weed/server/volume_server_handlers_write.go:19) and published
// 15k writes/s / 47k reads/s on one laptop core (README.md:504-538).  A
// Python ThreadingHTTPServer tops out ~50x lower, so this engine owns the
// volume server's public port with an epoll event loop and serves the hot
// needle ops (GET/HEAD/POST/PUT/DELETE on /<vid>,<fid>) directly against
// the .dat/.idx files; every other route (admin, status, metrics) is
// proxied verbatim to the Python daemon listening on an internal port.
//
// Ownership protocol: while a volume is "registered" here, THIS engine is
// the only writer of its .dat/.idx and the only authority on its needle
// map (the Python Volume delegates lookups/appends through the C API —
// see native/turbo.py TurboNeedleMap).  Python detaches (unregister) before
// any operation that rewrites files (vacuum, tier move, destroy) and
// re-attaches after.  On-disk formats are bit-compatible with the Python
// writer (storage/needle.py, storage/idx.py), which is itself
// bit-compatible with the Go reference (weed/storage/needle/needle_read_write.go).
//
// Concurrency: one epoll worker per thread, each with its own SO_REUSEPORT
// listener.  Volume state is shared: per-volume mutex for map/append;
// reads drop the mutex before pread (the .dat prefix is immutable).
// Unregister marks the volume dead under its mutex; in-flight ops holding
// the shared_ptr observe `dead` and fall back to proxying.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>
#include <zlib.h>

// ---------------------------------------------------------------------------
// CRC-32C (Castagnoli), matching storage/crc.py / weed/storage/needle/crc.go.
// Hardware SSE4.2 path when available, slicing-by-8 fallback.

static uint32_t crc_tab[8][256];

static void crc_init_tables() {
  for (int i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ ((c & 1) ? 0x82F63B78u : 0);
    crc_tab[0][i] = c;
  }
  for (int t = 1; t < 8; t++)
    for (int i = 0; i < 256; i++)
      crc_tab[t][i] = (crc_tab[t - 1][i] >> 8) ^ crc_tab[0][crc_tab[t - 1][i] & 0xFF];
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
  crc ^= 0xFFFFFFFFu;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    crc = crc_tab[7][crc & 0xFF] ^ crc_tab[6][(crc >> 8) & 0xFF] ^
          crc_tab[5][(crc >> 16) & 0xFF] ^ crc_tab[4][(crc >> 24) & 0xFF] ^
          crc_tab[3][p[4]] ^ crc_tab[2][p[5]] ^ crc_tab[1][p[6]] ^
          crc_tab[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ crc_tab[0][(crc ^ *p++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) static uint32_t crc32c_hw(uint32_t crc,
                                                            const uint8_t* p,
                                                            size_t n) {
  crc ^= 0xFFFFFFFFu;
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    n -= 8;
  }
  crc = (uint32_t)c;
  while (n--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return crc ^ 0xFFFFFFFFu;
}
static bool g_has_sse42 = false;
#endif

static uint32_t crc32c(const uint8_t* p, size_t n) {
#if defined(__x86_64__)
  if (g_has_sse42) return crc32c_hw(0, p, n);
#endif
  return crc32c_sw(0, p, n);
}

// masked on-disk value (crc.go:24-26): rotr32(crc,15) + 0xa282ead8
static uint32_t crc_masked(uint32_t crc) {
  uint32_t rot = (crc >> 15) | (crc << 17);
  return rot + 0xA282EAD8u;
}

static inline uint32_t be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) | ((uint32_t)p[2] << 8) | p[3];
}
static inline uint64_t be64(const uint8_t* p) {
  return ((uint64_t)be32(p) << 32) | be32(p + 4);
}
static inline void put_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
static inline void put_be64(uint8_t* p, uint64_t v) {
  put_be32(p, v >> 32);
  put_be32(p + 4, (uint32_t)v);
}

// ---------------------------------------------------------------------------
// SHA-256 + HMAC + base64url: enough crypto to verify the fid-scoped HS256
// JWTs (security/jwt.py gen_jwt / weed/security/jwt.go GenJwt) natively, so
// auth-enabled deployments keep the fast path instead of proxying.

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)p[i * 4] << 24) | ((uint32_t)p[i * 4 + 1] << 16) |
             ((uint32_t)p[i * 4 + 2] << 8) | p[i * 4 + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len += n;
    while (n > 0) {
      size_t take = std::min(n, (size_t)64 - buf_len);
      memcpy(buf + buf_len, p, take);
      buf_len += take;
      p += take;
      n -= take;
      if (buf_len == 64) {
        block(buf);
        buf_len = 0;
      }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = (bits >> (56 - 8 * i)) & 0xFF;
    update(lb, 8);
    for (int i = 0; i < 8; i++) put_be32(out + 4 * i, h[i]);
  }
};

static void hmac_sha256(const std::string& key, const std::string& msg,
                        uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 s;
    s.update((const uint8_t*)key.data(), key.size());
    s.final(k);
  } else {
    memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 si;
  si.update(ipad, 64);
  si.update((const uint8_t*)msg.data(), msg.size());
  si.final(inner);
  Sha256 so;
  so.update(opad, 64);
  so.update(inner, 32);
  so.final(out);
}

static bool b64url_decode(const std::string& in, std::string* out) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '-') return 62;
    if (c == '_') return 63;
    return -1;
  };
  out->clear();
  int acc = 0, nbits = 0;
  for (char c : in) {
    if (c == '=') break;
    int v = val(c);
    if (v < 0) return false;
    acc = (acc << 6) | v;
    nbits += 6;
    if (nbits >= 8) {
      nbits -= 8;
      out->push_back((char)((acc >> nbits) & 0xFF));
    }
  }
  return true;
}

// Verify a compact HS256 JWT scoped to `fid` (security/jwt.py
// verify_fid_jwt): signature, expiry, and exact fid claim.
static bool verify_fid_jwt(const std::string& key, const std::string& token,
                           const std::string& fid) {
  size_t d1 = token.find('.');
  if (d1 == std::string::npos) return false;
  size_t d2 = token.find('.', d1 + 1);
  if (d2 == std::string::npos) return false;
  std::string msg = token.substr(0, d2);
  std::string sig;
  if (!b64url_decode(token.substr(d2 + 1), &sig) || sig.size() != 32)
    return false;
  uint8_t want[32];
  hmac_sha256(key, msg, want);
  uint8_t diff = 0;
  for (int i = 0; i < 32; i++) diff |= want[i] ^ (uint8_t)sig[i];
  if (diff) return false;
  std::string payload;
  if (!b64url_decode(token.substr(d1 + 1, d2 - d1 - 1), &payload))
    return false;
  // claims are our own compact json: {"exp":N,"fid":"..."}
  size_t ep = payload.find("\"exp\":");
  if (ep == std::string::npos) return false;
  long long exp = strtoll(payload.c_str() + ep + 6, nullptr, 10);
  if (exp < (long long)time(nullptr)) return false;
  size_t fp = payload.find("\"fid\":\"");
  if (fp == std::string::npos) return false;
  size_t fs = fp + 7;
  size_t fe = payload.find('"', fs);
  if (fe == std::string::npos) return false;
  std::string claim = payload.substr(fs, fe - fs);
  for (auto& ch : claim)
    if (ch == '/') ch = ',';  // normalize vid/key vs vid,key
  return claim == fid;
}

// ---------------------------------------------------------------------------
// Needle/idx format constants (storage/types.py, storage/needle.py).

static const int NEEDLE_HEADER = 16;   // cookie u32BE | id u64BE | size u32BE
static const int CHECKSUM_SIZE = 4;
static const int TS_SIZE = 8;          // v3 append_at_ns
static const int PAD = 8;
static const int32_t TOMBSTONE = -1;

static const uint8_t FLAG_IS_COMPRESSED = 0x01;
static const uint8_t FLAG_HAS_NAME = 0x02;
static const uint8_t FLAG_HAS_MIME = 0x04;
static const uint8_t FLAG_HAS_LAST_MODIFIED = 0x08;
static const uint8_t FLAG_HAS_TTL = 0x10;
static const uint8_t FLAG_HAS_PAIRS = 0x20;
static const uint8_t FLAG_IS_CHUNK_MANIFEST = 0x80;

// padding after the record — always 1..8 (needle_read_write.go:298-304)
static int padding_len(int64_t needle_size, int version) {
  int64_t used = NEEDLE_HEADER + needle_size + CHECKSUM_SIZE +
                 (version == 3 ? TS_SIZE : 0);
  return PAD - (used % PAD);
}
static int64_t body_len(int64_t needle_size, int version) {
  return needle_size + CHECKSUM_SIZE + (version == 3 ? TS_SIZE : 0) +
         padding_len(needle_size, version);
}
static int64_t actual_size(int64_t needle_size, int version) {
  return NEEDLE_HEADER + body_len(needle_size, version);
}

// TTL minutes (storage/ttl.py): units minute..year stored 1..6
static int64_t ttl_minutes(uint8_t count, uint8_t unit) {
  static const int64_t mult[] = {0, 1, 60, 60 * 24, 60 * 24 * 7, 60 * 24 * 31,
                                 60 * 24 * 365};
  if (unit > 6) return 0;
  return (int64_t)count * mult[unit];
}

// ---------------------------------------------------------------------------
// Per-volume needle map: open-addressing, linear probing, power-of-2 table.
// 24B/slot; EMPTY key sentinel 0xFFFF..FF (never issued by the sequencer).

struct Slot {
  uint64_t key;
  uint64_t off;    // actual byte offset
  int32_t size;    // negative = deleted (original size negated), -1 tombstone
};
static const uint64_t EMPTY_KEY = ~0ULL;

struct NeedleMap {
  std::vector<Slot> slots;
  size_t used = 0;

  NeedleMap() { slots.assign(1024, Slot{EMPTY_KEY, 0, 0}); }

  Slot* find(uint64_t key) {
    size_t mask = slots.size() - 1;
    size_t i = (key * 0x9E3779B97F4A7C15ULL) & mask;
    while (true) {
      Slot& s = slots[i];
      if (s.key == key) return &s;
      if (s.key == EMPTY_KEY) return nullptr;
      i = (i + 1) & mask;
    }
  }

  void grow() {
    std::vector<Slot> old;
    old.swap(slots);
    slots.assign(old.size() * 2, Slot{EMPTY_KEY, 0, 0});
    size_t mask = slots.size() - 1;
    for (const Slot& s : old) {
      if (s.key == EMPTY_KEY) continue;
      size_t i = (s.key * 0x9E3779B97F4A7C15ULL) & mask;
      while (slots[i].key != EMPTY_KEY) i = (i + 1) & mask;
      slots[i] = s;
    }
  }

  // returns pointer to the (possibly pre-existing) slot
  Slot* upsert(uint64_t key, uint64_t off, int32_t size, bool* existed) {
    if (used * 10 >= slots.size() * 7) grow();
    size_t mask = slots.size() - 1;
    size_t i = (key * 0x9E3779B97F4A7C15ULL) & mask;
    while (true) {
      Slot& s = slots[i];
      if (s.key == key) {
        *existed = true;
        s.off = off;
        s.size = size;
        return &s;
      }
      if (s.key == EMPTY_KEY) {
        *existed = false;
        s = Slot{key, off, size};
        used++;
        return &s;
      }
      i = (i + 1) & mask;
    }
  }
};

struct Vol {
  uint32_t vid;
  int dat_fd = -1;
  int idx_fd = -1;
  int version = 3;
  int offset_size = 4;  // 4 or 5 byte idx offsets
  bool writable_http = true;
  std::atomic<bool> read_only{false};
  std::atomic<bool> dead{false};

  std::mutex mu;
  NeedleMap map;
  uint64_t append_off = 0;
  uint64_t idx_size = 0;
  // mapMetric counters (storage/needle_map.py IdxLogMixin semantics)
  uint64_t file_count = 0, file_bytes = 0, del_count = 0, del_bytes = 0;
  uint64_t max_key = 0;
  uint64_t last_modified_s = 0;
  uint64_t last_append_ns = 0;

  ~Vol() {
    if (dat_fd >= 0) close(dat_fd);
    if (idx_fd >= 0) close(idx_fd);
  }

  int entry_size() const { return 8 + offset_size + 4; }

  // CompactNeedleMap.put counter semantics (needle_map.py:153-163)
  void apply_put(uint64_t key, uint64_t off, int32_t size) {
    bool existed;
    Slot* s = map.find(key);
    int32_t old_size = s ? s->size : 0;
    uint64_t old_off = s ? s->off : 0;
    map.upsert(key, off, size, &existed);
    if (key > max_key && key != EMPTY_KEY) max_key = key;
    file_count++;
    file_bytes += (uint32_t)size;
    if (existed && old_off != 0 && old_size > 0 && old_size != TOMBSTONE) {
      del_count++;
      del_bytes += (uint32_t)old_size;
    }
  }

  // CompactNeedleMap.delete semantics: keep original offset, negate size
  void apply_delete(uint64_t key) {
    Slot* s = map.find(key);
    del_count++;
    if (s && s->size > 0 && s->size != TOMBSTONE) {
      del_bytes += (uint32_t)s->size;
      s->size = -s->size;
    }
  }

  // max representable byte offset for this volume's idx flavor
  uint64_t max_offset() const {
    return (offset_size == 4 ? 0xFFFFFFFFull : 0xFFFFFFFFFFull) * PAD;
  }

  int write_idx_entry(uint64_t key, uint64_t off, int32_t size) {
    uint8_t e[17];
    put_be64(e, key);
    uint64_t scaled = off / PAD;
    if (scaled > (offset_size == 4 ? 0xFFFFFFFFull : 0xFFFFFFFFFFull))
      return -1;  // never persist a truncated offset (types.py raises here)
    if (offset_size == 4) {
      put_be32(e + 8, (uint32_t)scaled);
      put_be32(e + 12, (uint32_t)size);
    } else {
      put_be32(e + 8, (uint32_t)(scaled & 0xFFFFFFFFu));
      e[12] = (uint8_t)(scaled >> 32);
      put_be32(e + 13, (uint32_t)size);
    }
    int n = entry_size();
    if (pwrite(idx_fd, e, n, idx_size) != n) return -1;
    idx_size += n;
    return 0;
  }
};

// ---------------------------------------------------------------------------
// Engine: registry + HTTP workers.

struct Engine {
  std::shared_mutex reg_mu;
  std::unordered_map<uint32_t, std::shared_ptr<Vol>> vols;

  std::string backend_ip;
  int backend_port = 0;
  std::string bind_ip;
  int port = 0;
  // fid-scoped JWT keys (set before workers serve traffic; empty = open)
  std::string jwt_write_key, jwt_read_key;

  std::vector<std::thread> workers;
  std::vector<int> stop_fds;  // eventfd per worker
  std::atomic<bool> stopping{false};

  // counters for /metrics merge
  std::atomic<uint64_t> n_get{0}, n_post{0}, n_delete{0}, n_proxy{0};

  std::shared_ptr<Vol> get_vol(uint32_t vid) {
    std::shared_lock<std::shared_mutex> lk(reg_mu);
    auto it = vols.find(vid);
    return it == vols.end() ? nullptr : it->second;
  }
};

// ---------------------------------------------------------------------------
// HTTP plumbing.

struct Conn {
  int fd;
  std::string in;     // unparsed request bytes
  std::string out;    // pending response bytes (EAGAIN backlog)
  bool close_after = false;
};

struct Worker {
  Engine* eng;
  int epfd = -1;
  int listen_fd = -1;
  int stop_fd = -1;
  // Proxied requests run in detached threads (a blocking proxy inside the
  // event loop would deadlock when the Python handler calls back into the
  // public port — e.g. manifest delete cascading to chunk deletes).  The
  // thread reports completion here; notify_fd wakes the loop to finalize.
  int notify_fd = -1;
  std::mutex done_mu;
  std::vector<std::pair<Conn*, bool>> done;
  std::atomic<int> inflight{0};
  // set when teardown abandons a wedged proxy thread: the Worker must be
  // leaked, not freed (the thread will still touch done_mu/notify_fd)
  std::atomic<bool> leak{false};
  std::unordered_map<int, Conn*> conns;
};

static int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

static int make_listener(const char* ip, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  if (strcmp(ip, "") == 0 || strcmp(ip, "0.0.0.0") == 0)
    a.sin_addr.s_addr = INADDR_ANY;
  else if (inet_pton(AF_INET, ip, &a.sin_addr) != 1) {
    // hostname like "localhost": fall back to loopback
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  }
  if (bind(fd, (sockaddr*)&a, sizeof(a)) < 0 || listen(fd, 1024) < 0) {
    close(fd);
    return -1;
  }
  set_nonblock(fd);
  return fd;
}

// best-effort immediate send; remainder buffered in conn->out
static bool conn_send(Worker* w, Conn* c, const char* data, size_t len) {
  if (c->out.empty()) {
    while (len > 0) {
      ssize_t n = send(c->fd, data, len, MSG_NOSIGNAL);
      if (n > 0) {
        data += n;
        len -= n;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      return false;  // peer gone
    }
  }
  if (len > 0) {
    c->out.append(data, len);
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = c->fd;
    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
  return true;
}

// blocking send used inside proxy streaming (worker is committed anyway)
static bool send_all_blocking(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = send(fd, data, len, MSG_NOSIGNAL);
    if (n > 0) {
      data += n;
      len -= n;
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      pollfd p{fd, POLLOUT, 0};
      poll(&p, 1, 10000);
      continue;
    }
    return false;
  }
  return true;
}

static const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 201: return "Created";
    case 202: return "Accepted";
    case 204: return "No Content";
    case 206: return "Partial Content";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 411: return "Length Required";
    case 413: return "Payload Too Large";
    case 416: return "Range Not Satisfiable";
    case 500: return "Internal Server Error";
    case 502: return "Bad Gateway";
    case 503: return "Service Unavailable";
    default: return "OK";
  }
}

// header + body in ONE sendmsg (MSG_NOSIGNAL: no SIGPIPE on dead peers):
// two send()s per GET meant two packets on loopback and often two client
// select()+recv() rounds per request — measurable at small-file rps scale.
static bool conn_send2(Worker* w, Conn* c, const char* hdr, size_t hlen,
                       const char* body, size_t blen) {
  if (!c->out.empty()) {  // EPOLLOUT already armed; just queue
    c->out.append(hdr, hlen);
    c->out.append(body, blen);
    return true;
  }
  iovec iov[2] = {{(void*)hdr, hlen}, {(void*)body, blen}};
  int idx = 0;  // a zero-length body iov is harmless; skipping hdr is not
  while (idx < 2) {
    msghdr msg{};
    msg.msg_iov = iov + idx;
    msg.msg_iovlen = 2 - idx;
    ssize_t n = sendmsg(c->fd, &msg, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      return false;  // peer gone
    }
    size_t left = n;
    while (idx < 2 && left >= iov[idx].iov_len) {
      left -= iov[idx].iov_len;
      idx++;
    }
    if (idx < 2) {
      iov[idx].iov_base = (char*)iov[idx].iov_base + left;
      iov[idx].iov_len -= left;
    }
  }
  if (idx < 2) {
    for (int j = idx; j < 2; j++)
      c->out.append((const char*)iov[j].iov_base, iov[j].iov_len);
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = c->fd;
    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
  return true;
}

static bool reply(Worker* w, Conn* c, int code, const char* ctype,
                  const char* extra_headers, const char* body, size_t body_len,
                  bool head_only) {
  char hdr[512];
  int hn = snprintf(hdr, sizeof(hdr),
                    "HTTP/1.1 %d %s\r\nContent-Type: %s\r\nContent-Length: %zu\r\n%s%s\r\n",
                    code, status_text(code), ctype, body_len,
                    extra_headers ? extra_headers : "",
                    c->close_after ? "Connection: close\r\n" : "");
  if (head_only || body_len == 0) return conn_send(w, c, hdr, hn);
  return conn_send2(w, c, hdr, hn, body, body_len);
}

static bool reply_json(Worker* w, Conn* c, int code, const std::string& js,
                       bool head_only = false) {
  return reply(w, c, code, "application/json", nullptr, js.data(), js.size(),
               head_only);
}

// ---------------------------------------------------------------------------
// Request model.

struct Req {
  const char* method;   // points into buffer
  size_t method_len;
  std::string path;     // path without query
  std::string query;    // raw query string
  size_t header_end;    // offset just past \r\n\r\n
  int64_t content_length = 0;
  bool conn_close = false;
  bool has_te_chunked = false;
  std::string range, name, mime, content_encoding, bearer;
  bool accepts_gzip = false;
  bool chunk_manifest = false;
  size_t total_len;     // header + body length in the buffer
  const uint8_t* body;
};

static bool ieq(const char* a, size_t alen, const char* b) {
  size_t blen = strlen(b);
  if (alen != blen) return false;
  for (size_t i = 0; i < alen; i++)
    if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i])) return false;
  return true;
}

static std::string q_get(const std::string& query, const char* key) {
  size_t klen = strlen(key);
  size_t i = 0;
  while (i < query.size()) {
    size_t amp = query.find('&', i);
    if (amp == std::string::npos) amp = query.size();
    size_t eq = query.find('=', i);
    if (eq != std::string::npos && eq < amp && (eq - i) == klen &&
        memcmp(query.data() + i, key, klen) == 0)
      return query.substr(eq + 1, amp - eq - 1);
    if (eq == std::string::npos || eq >= amp) {  // bare key
      if (amp - i == klen && memcmp(query.data() + i, key, klen) == 0) return "";
    }
    i = amp + 1;
  }
  return std::string("\x01");  // sentinel: absent (distinct from empty)
}
static bool q_has(const std::string& query, const char* key) {
  std::string v = q_get(query, key);
  return !(v.size() == 1 && v[0] == '\x01');
}

// parse one request from buf; returns 0 = need more, 1 = ok, -1 = bad
static int parse_request(const std::string& buf, Req* r) {
  size_t he = buf.find("\r\n\r\n");
  if (he == std::string::npos) {
    if (buf.size() > 65536) return -1;
    return 0;
  }
  r->header_end = he + 4;
  // request line
  size_t eol = buf.find("\r\n");
  size_t sp1 = buf.find(' ');
  if (sp1 == std::string::npos || sp1 > eol) return -1;
  size_t sp2 = buf.find(' ', sp1 + 1);
  if (sp2 == std::string::npos || sp2 > eol) return -1;
  r->method = buf.data();
  r->method_len = sp1;
  std::string target = buf.substr(sp1 + 1, sp2 - sp1 - 1);
  size_t qm = target.find('?');
  if (qm == std::string::npos) {
    r->path = target;
    r->query.clear();
  } else {
    r->path = target.substr(0, qm);
    r->query = target.substr(qm + 1);
  }
  // headers
  size_t i = eol + 2;
  while (i < he) {
    size_t lend = buf.find("\r\n", i);
    if (lend == std::string::npos || lend > he) lend = he;
    size_t colon = buf.find(':', i);
    if (colon != std::string::npos && colon < lend) {
      const char* k = buf.data() + i;
      size_t klen = colon - i;
      size_t vstart = colon + 1;
      while (vstart < lend && buf[vstart] == ' ') vstart++;
      std::string v = buf.substr(vstart, lend - vstart);
      if (ieq(k, klen, "content-length"))
        r->content_length = strtoll(v.c_str(), nullptr, 10);
      else if (ieq(k, klen, "connection")) {
        for (auto& ch : v) ch = tolower((unsigned char)ch);
        if (v.find("close") != std::string::npos) r->conn_close = true;
      } else if (ieq(k, klen, "transfer-encoding")) {
        r->has_te_chunked = true;
      } else if (ieq(k, klen, "range"))
        r->range = v;
      else if (ieq(k, klen, "x-sweed-name"))
        r->name = v;
      else if (ieq(k, klen, "x-sweed-mime"))
        r->mime = v;
      else if (ieq(k, klen, "x-sweed-chunk-manifest"))
        r->chunk_manifest = (v == "true");
      else if (ieq(k, klen, "content-encoding"))
        r->content_encoding = v;
      else if (ieq(k, klen, "authorization")) {
        if (v.compare(0, 7, "Bearer ") == 0) r->bearer = v.substr(7);
      } else if (ieq(k, klen, "accept-encoding")) {
        if (v.find("gzip") != std::string::npos) r->accepts_gzip = true;
      }
    }
    i = lend + 2;
  }
  if (r->has_te_chunked) return -1;  // CL-framed only (411 upstream)
  // Reject oversize bodies at header-parse time, BEFORE the read loop
  // buffers them: needles are bounded at 1 GiB (handle_post's 413) and no
  // inbound endpoint takes more (volume copy is pull-based), so anything
  // past 1 GiB + multipart/header slack can only be a memory-bloat attack.
  static const int64_t MAX_BODY = ((int64_t)1 << 30) + (16 << 20);
  if (r->content_length < 0 || r->content_length > MAX_BODY) return -1;
  if (buf.size() < r->header_end + (size_t)r->content_length) return 0;
  r->total_len = r->header_end + (size_t)r->content_length;
  r->body = (const uint8_t*)buf.data() + r->header_end;
  return 1;
}

// ---------------------------------------------------------------------------
// fid parsing: /<vid>,<idhex><cookie8>[_delta][.ext]  (file_id.py)

struct Fid {
  uint32_t vid;
  uint64_t key;
  uint32_t cookie;
  std::string str;  // "vid,hex[_delta]" — the JWT claim form (_auth_ok)
};

static bool parse_fid_path(const std::string& path, Fid* f) {
  size_t i = 1;  // skip leading /
  if (i >= path.size() || !isdigit((unsigned char)path[i])) return false;
  uint64_t vid = 0;
  while (i < path.size() && isdigit((unsigned char)path[i])) {
    vid = vid * 10 + (path[i] - '0');
    if (vid > 0xFFFFFFFFull) return false;
    i++;
  }
  if (i >= path.size() || (path[i] != ',' && path[i] != '/')) return false;
  i++;
  std::string fid = path.substr(i);
  if (fid.find('/') != std::string::npos) return false;
  // strip extension (volume server strips from rindex('.'))
  size_t dot = fid.rfind('.');
  if (dot != std::string::npos) fid = fid.substr(0, dot);
  // JWT claim form BEFORE the delta split (volume_server._auth_ok builds
  // "vid,hex[_delta]" the same way — ext stripped, first sep → comma)
  f->str = std::to_string(vid) + "," + fid;
  // _delta suffix (chunked uploads, needle.go:120-142)
  uint64_t delta = 0;
  size_t us = fid.rfind('_');
  if (us != std::string::npos) {
    for (size_t k = us + 1; k < fid.size(); k++) {
      if (!isdigit((unsigned char)fid[k])) return false;
      delta = delta * 10 + (fid[k] - '0');
    }
    fid = fid.substr(0, us);
  }
  if (fid.size() <= 8 || fid.size() > 24) return false;
  for (char ch : fid)
    if (!isxdigit((unsigned char)ch)) return false;
  size_t split = fid.size() - 8;
  uint64_t base = strtoull(fid.substr(0, split).c_str(), nullptr, 16);
  if (delta > ~0ULL - base) return false;  // key+delta would wrap
  uint64_t key = base + delta;
  // ~0ULL is the needle map's EMPTY_KEY slot sentinel; a record stored under
  // it would vanish on the next table grow. Fall through to the Python proxy,
  // whose dict-backed map has no sentinel.
  if (key == EMPTY_KEY) return false;
  f->vid = (uint32_t)vid;
  f->key = key;
  f->cookie = (uint32_t)strtoul(fid.substr(split).c_str(), nullptr, 16);
  return true;
}

// fid-scoped auth gate (volume_server._auth_ok): query `auth` wins, then
// the Bearer header; empty key = open.
static bool auth_ok(const std::string& key, const Req& r, const Fid& f) {
  if (key.empty()) return true;
  std::string token = q_get(r.query, "auth");
  if (token.size() == 1 && token[0] == '\x01') token.clear();  // absent
  if (token.empty()) token = r.bearer;
  return verify_fid_jwt(key, token, f.str);
}

static std::string hexkey(uint64_t key) {
  char b[20];
  snprintf(b, sizeof(b), "%llx", (unsigned long long)key);
  return b;
}

// ---------------------------------------------------------------------------
// Proxy: forward the raw request to the Python backend, stream the response.

static int backend_connect(Engine* e) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(e->backend_port);
  if (inet_pton(AF_INET, e->backend_ip.c_str(), &a.sin_addr) != 1)
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (connect(fd, (sockaddr*)&a, sizeof(a)) < 0) {
    close(fd);
    return -1;
  }
  return fd;
}

static bool send_502(int cfd, const char* msg) {
  char b[256];
  int blen = snprintf(b, sizeof(b),
                      "HTTP/1.1 502 Bad Gateway\r\nContent-Type: application/json\r\n"
                      "Content-Length: %zu\r\n\r\n%s",
                      strlen(msg), msg);
  return send_all_blocking(cfd, b, blen);
}

// Blocking proxy, runs in its own detached thread with its own backend
// connection.  Returns true if the client connection is still usable.
static bool proxy_blocking(Engine* e, int cfd, const std::string& raw,
                           bool is_head) {
  e->n_proxy++;
  int bfd = backend_connect(e);
  if (bfd < 0) return send_502(cfd, "{\"error\": \"backend unreachable\"}");
  bool client_ok = true;
  bool done = false;
  // forward raw request bytes
  size_t off = 0;
  while (off < raw.size()) {
    ssize_t n = send(bfd, raw.data() + off, raw.size() - off, MSG_NOSIGNAL);
    if (n <= 0) {
      client_ok = send_502(cfd, "{\"error\": \"backend send failed\"}");
      done = true;
      break;
    }
    off += n;
  }
  std::string rh;
  char buf[65536];
  size_t he = 0;
  while (!done) {  // response headers
    he = rh.find("\r\n\r\n");
    if (he != std::string::npos) break;
    if (rh.size() > 65536) {
      client_ok = send_502(cfd, "{\"error\": \"backend header overflow\"}");
      done = true;
      break;
    }
    ssize_t n = recv(bfd, buf, sizeof(buf), 0);
    if (n <= 0) {
      client_ok = send_502(cfd, "{\"error\": \"backend closed\"}");
      done = true;
      break;
    }
    rh.append(buf, n);
  }
  if (!done) {
    he += 4;
    int64_t cl = -1;
    {
      size_t i = rh.find("\r\n") + 2;
      while (i < he - 2) {
        size_t lend = rh.find("\r\n", i);
        size_t colon = rh.find(':', i);
        if (colon != std::string::npos && colon < lend) {
          const char* k = rh.data() + i;
          size_t klen = colon - i;
          size_t vs = colon + 1;
          while (vs < lend && rh[vs] == ' ') vs++;
          if (ieq(k, klen, "content-length"))
            cl = strtoll(rh.c_str() + vs, nullptr, 10);
        }
        i = lend + 2;
      }
    }
    if (!send_all_blocking(cfd, rh.data(), rh.size())) {
      client_ok = false;
    } else {
      int64_t have = rh.size() - he;
      int64_t remaining = is_head ? 0 : (cl >= 0 ? cl - have : -1);
      while (remaining != 0) {
        ssize_t n = recv(bfd, buf,
                         remaining < 0 ? sizeof(buf)
                                       : (size_t)std::min<int64_t>(
                                             remaining, sizeof(buf)),
                         0);
        if (n <= 0) {
          // close-delimited body done, or truncated CL body (framing broken)
          client_ok = remaining < 0;
          break;
        }
        if (!send_all_blocking(cfd, buf, n)) {
          client_ok = false;
          break;
        }
        if (remaining > 0) remaining -= n;
      }
      if (cl < 0) client_ok = false;  // close-delimited: framing consumed
    }
  }
  close(bfd);
  return client_ok;
}

// ---------------------------------------------------------------------------
// Data-plane handlers.

static uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

// Parse a needle record body; returns data pointer/len + flags (v2/v3).
struct ParsedNeedle {
  const uint8_t* data;
  int64_t data_len;
  uint8_t flags;
  uint64_t last_modified = 0;
  uint8_t ttl_count = 0, ttl_unit = 0;
  bool ok;
};

static ParsedNeedle parse_needle_record(const uint8_t* rec, int64_t size,
                                        int version) {
  ParsedNeedle p{nullptr, 0, 0, 0, 0, 0, false};
  if (version == 1) {
    p.data = rec + NEEDLE_HEADER;
    p.data_len = size;
    p.flags = 0;
    p.ok = true;
    return p;
  }
  const uint8_t* b = rec + NEEDLE_HEADER;
  int64_t n = size;
  int64_t idx = 0;
  if (idx < n) {
    if (idx + 4 > n) return p;
    int64_t dlen = be32(b + idx);
    idx += 4;
    if (dlen + idx >= n) return p;  // flags byte must follow
    p.data = b + idx;
    p.data_len = dlen;
    idx += dlen;
    p.flags = b[idx];
    idx += 1;
  }
  if (idx < n && (p.flags & FLAG_HAS_NAME)) {
    int64_t l = b[idx];
    idx += 1 + l;
    if (idx > n) return p;
  }
  if (idx < n && (p.flags & FLAG_HAS_MIME)) {
    int64_t l = b[idx];
    idx += 1 + l;
    if (idx > n) return p;
  }
  if (idx < n && (p.flags & FLAG_HAS_LAST_MODIFIED)) {
    if (idx + 5 > n) return p;
    for (int k = 0; k < 5; k++) p.last_modified = (p.last_modified << 8) | b[idx + k];
    idx += 5;
  }
  if (idx < n && (p.flags & FLAG_HAS_TTL)) {
    if (idx + 2 > n) return p;
    p.ttl_count = b[idx];
    p.ttl_unit = b[idx + 1];
    idx += 2;
  }
  p.ok = true;
  return p;
}

// single-range parser matching http_util.parse_byte_range
// ret: 0 = serve full, 1 = range [start,end], 2 = unsatisfiable
static int parse_range(const std::string& spec, int64_t total, int64_t* start,
                       int64_t* end) {
  if (spec.compare(0, 6, "bytes=") != 0) return 0;
  if (spec.find(',') != std::string::npos) return 0;
  std::string s = spec.substr(6);
  size_t dash = s.find('-');
  if (dash == std::string::npos) return 0;
  std::string a = s.substr(0, dash), b = s.substr(dash + 1);
  int64_t st, en;
  auto is_num = [](const std::string& x) {
    if (x.empty()) return false;
    for (char c : x) if (!isdigit((unsigned char)c)) return false;
    return true;
  };
  if (a.empty()) {
    if (!is_num(b)) return 0;
    st = total - strtoll(b.c_str(), nullptr, 10);
    if (st < 0) st = 0;
    en = total - 1;
  } else {
    if (!is_num(a) || (!b.empty() && !is_num(b))) return 0;
    st = strtoll(a.c_str(), nullptr, 10);
    en = b.empty() ? total - 1 : strtoll(b.c_str(), nullptr, 10);
  }
  if (en > total - 1) en = total - 1;
  if (st > en || st >= total) return 2;
  *start = st;
  *end = en;
  return 1;
}

// GET/HEAD on a fid.  Returns: 0 handled, 1 proxy-me, -1 client dead.
static int handle_get(Worker* w, Conn* c, const Req& r, const Fid& f,
                      bool head_only) {
  Engine* e = w->eng;
  auto vol = e->get_vol(f.vid);
  if (!vol || vol->dead.load()) return 1;
  if (!auth_ok(e->jwt_read_key, r, f))
    return reply_json(w, c, 401, "{\"error\": \"unauthorized read\"}",
                      head_only) ? 0 : -1;
  if (q_has(r.query, "width") || q_has(r.query, "height") || q_has(r.query, "cm"))
    return 1;  // image resize / manifest-control paths stay in Python

  uint64_t off;
  int32_t size;
  {
    std::lock_guard<std::mutex> lk(vol->mu);
    if (vol->dead.load()) return 1;
    Slot* s = vol->map.find(f.key);
    if (!s || s->off == 0) {
      e->n_get++;
      return reply_json(w, c, 404,
                        "{\"error\": \"needle " + hexkey(f.key) + " not found\"}",
                        head_only) ? 0 : -1;
    }
    if (s->size < 0) {
      e->n_get++;
      return reply_json(w, c, 404,
                        "{\"error\": \"needle " + hexkey(f.key) + " deleted\"}",
                        head_only) ? 0 : -1;
    }
    off = s->off;
    size = s->size;
  }
  e->n_get++;
  if (size == 0)
    return reply(w, c, 200, "application/octet-stream",
                 "Accept-Ranges: bytes\r\n", "", 0, head_only) ? 0 : -1;

  int64_t rec_len = actual_size(size, vol->version);
  // per-worker scratch for the common small-needle case: no per-request
  // malloc + zero-fill. Big records get a one-off buffer instead so a
  // single large GET can't pin megabytes of worker RSS forever.
  static const int64_t SCRATCH_MAX = 4 << 20;
  static thread_local std::vector<uint8_t> scratch;
  std::vector<uint8_t> big;
  std::vector<uint8_t>& rec = rec_len <= SCRATCH_MAX ? scratch : big;
  if (rec.size() < (size_t)rec_len) rec.resize(rec_len);
  ssize_t got = pread(vol->dat_fd, rec.data(), rec_len, off);
  if (got != rec_len)
    return reply_json(w, c, 500, "{\"error\": \"short read from .dat\"}",
                      head_only) ? 0 : -1;
  uint32_t disk_cookie = be32(rec.data());
  if (disk_cookie != f.cookie)
    return reply_json(w, c, 404, "{\"error\": \"cookie mismatch\"}", head_only)
               ? 0 : -1;
  ParsedNeedle p = parse_needle_record(rec.data(), size, vol->version);
  if (!p.ok)
    return reply_json(w, c, 500, "{\"error\": \"corrupt needle body\"}",
                      head_only) ? 0 : -1;
  if (p.flags & FLAG_IS_CHUNK_MANIFEST)
    return 1;  // manifest resolution (cross-needle assembly) lives in Python
  // CRC (read_needle verifies on every read; covers the stored bytes)
  uint32_t stored = be32(rec.data() + NEEDLE_HEADER + size);
  if (stored != crc_masked(crc32c(p.data, p.data_len)))
    return reply_json(w, c, 500,
                      "{\"error\": \"CrcError: CRC error! data on disk corrupted\"}",
                      head_only) ? 0 : -1;
  // TTL expiry (volume.py read_needle:414-424) — checked BEFORE any
  // decompression work: an expired needle must cost nothing but a 404
  if ((p.flags & FLAG_HAS_TTL) && (p.flags & FLAG_HAS_LAST_MODIFIED)) {
    int64_t mins = ttl_minutes(p.ttl_count, p.ttl_unit);
    if (mins > 0 && (int64_t)time(nullptr) >= (int64_t)p.last_modified + mins * 60)
      return reply_json(w, c, 404,
                        "{\"error\": \"needle " + hexkey(f.key) + " expired\"}",
                        head_only) ? 0 : -1;
  }
  // gzip'd needles (volume_server.py _h_get:176-188): clients that accept
  // gzip get the stored bytes verbatim + Content-Encoding (ranges are then
  // NOT applied — they would address the plaintext); everyone else gets an
  // inflate right here instead of a proxy hop to Python
  std::string inflated;
  bool serving_gzip = false;
  if (p.flags & FLAG_IS_COMPRESSED) {
    if (r.accepts_gzip) {
      serving_gzip = true;
    } else {
      // bounded + exception-safe: a gzip bomb must 500 this request, not
      // bad_alloc-terminate the process; multi-member streams (legal per
      // RFC 1952, decoded fully by Python's gzip.decompress) reset and
      // continue until the input is consumed
      const size_t MAX_PLAIN = (size_t)1 << 30;
      z_stream zs{};
      if (inflateInit2(&zs, 15 + 32) != Z_OK)  // gzip or zlib wrapper
        return reply_json(w, c, 500, "{\"error\": \"inflate init failed\"}",
                          head_only) ? 0 : -1;
      zs.next_in = (Bytef*)p.data;
      zs.avail_in = (uInt)p.data_len;
      size_t out_len = 0;
      bool bad = false, too_big = false;
      try {
        inflated.resize(std::min<size_t>(
            MAX_PLAIN, std::max<size_t>((size_t)p.data_len * 4, 4096)));
        while (true) {
          if (out_len == inflated.size()) {
            if (inflated.size() >= MAX_PLAIN) { too_big = true; break; }
            inflated.resize(std::min(MAX_PLAIN, inflated.size() * 2));
          }
          zs.next_out = (Bytef*)inflated.data() + out_len;
          zs.avail_out = (uInt)(inflated.size() - out_len);
          int ret = inflate(&zs, Z_NO_FLUSH);
          out_len = inflated.size() - zs.avail_out;
          if (ret == Z_STREAM_END) {
            if (zs.avail_in == 0) break;       // fully consumed
            if (inflateReset2(&zs, 15 + 32) != Z_OK) { bad = true; break; }
            continue;                           // next gzip member
          }
          if (ret != Z_OK) { bad = true; break; }
        }
      } catch (const std::exception&) {
        bad = true;  // length_error / bad_alloc from resize
      }
      inflateEnd(&zs);
      if (too_big)
        return reply_json(w, c, 500,
                          "{\"error\": \"decompressed needle too large\"}",
                          head_only) ? 0 : -1;
      if (bad)
        return reply_json(w, c, 500, "{\"error\": \"corrupt gzip needle\"}",
                          head_only) ? 0 : -1;
      inflated.resize(out_len);
      p.data = (const uint8_t*)inflated.data();
      p.data_len = (int64_t)inflated.size();
    }
  }
  if (serving_gzip)
    return reply(w, c, 200, "application/octet-stream",
                 "Content-Encoding: gzip\r\nAccept-Ranges: bytes\r\n",
                 (const char*)p.data, p.data_len, head_only) ? 0 : -1;
  if (!r.range.empty()) {
    int64_t st = 0, en = 0;
    int kind = parse_range(r.range, p.data_len, &st, &en);
    if (kind == 2) {
      char xh[64];
      snprintf(xh, sizeof(xh), "Content-Range: bytes */%lld\r\n",
               (long long)p.data_len);
      return reply(w, c, 416, "application/octet-stream", xh, "", 0, head_only)
                 ? 0 : -1;
    }
    if (kind == 1) {
      char xh[128];
      snprintf(xh, sizeof(xh),
               "Content-Range: bytes %lld-%lld/%lld\r\nAccept-Ranges: bytes\r\n",
               (long long)st, (long long)en, (long long)p.data_len);
      return reply(w, c, 206, "application/octet-stream", xh,
                   (const char*)p.data + st, en - st + 1, head_only) ? 0 : -1;
    }
  }
  return reply(w, c, 200, "application/octet-stream", "Accept-Ranges: bytes\r\n",
               (const char*)p.data, p.data_len, head_only) ? 0 : -1;
}

// POST/PUT on a fid.  Returns: 0 handled, 1 proxy-me, -1 client dead.
static int handle_post(Worker* w, Conn* c, const Req& r, const Fid& f) {
  Engine* e = w->eng;
  auto vol = e->get_vol(f.vid);
  if (!vol || vol->dead.load()) return 1;
  if (!auth_ok(e->jwt_write_key, r, f))
    return reply_json(w, c, 401, "{\"error\": \"unauthorized write\"}")
               ? 0 : -1;
  if (!vol->writable_http || vol->version != 3) return 1;  // replication/old fmt
  if (q_has(r.query, "ttl")) return 1;  // needle-level TTL writes stay in Python
  if (vol->read_only.load())
    return reply_json(w, c, 500,
                      "{\"error\": \"VolumeError: volume " +
                          std::to_string(f.vid) + " is read only\"}") ? 0 : -1;

  const uint8_t* data = r.body;
  int64_t dlen = r.content_length;
  // the needle `size` field is int32; bound bodies well below it (the
  // Python path fails loudly at struct-pack time — silently casting here
  // would poison the map/idx with a negative size). Big objects go
  // through chunking (operation.submit -maxMB / the filer) anyway.
  if (dlen > ((int64_t)1 << 30))
    return reply_json(w, c, 413,
                      "{\"error\": \"body too large for a single needle\"}")
               ? 0 : -1;
  uint8_t flags = FLAG_HAS_LAST_MODIFIED;  // volume_server.py _h_post always sets
  std::string name = r.name.substr(0, 255);
  std::string mime = r.mime.substr(0, 255);
  if (!name.empty()) flags |= FLAG_HAS_NAME;
  if (!mime.empty()) flags |= FLAG_HAS_MIME;
  if (r.content_encoding == "gzip") flags |= FLAG_IS_COMPRESSED;
  if (r.chunk_manifest) flags |= FLAG_IS_CHUNK_MANIFEST;

  // needle `size` field (needle.py _computed_size)
  int64_t size = 0;
  if (dlen > 0) {
    size = 4 + dlen + 1;
    if (flags & FLAG_HAS_NAME) size += 1 + name.size();
    if (flags & FLAG_HAS_MIME) size += 1 + mime.size();
    size += 5;  // last_modified
  }
  uint32_t crc = crc32c(data, dlen);
  uint64_t lm = (uint64_t)time(nullptr);
  uint64_t ns = now_ns();
  int pad = padding_len(size, 3);
  int64_t rec_len = NEEDLE_HEADER + size + CHECKSUM_SIZE + TS_SIZE + pad;

  std::vector<uint8_t> rec(rec_len);
  uint8_t* o = rec.data();
  put_be32(o, f.cookie);
  put_be64(o + 4, f.key);
  put_be32(o + 12, (uint32_t)size);
  int64_t i = NEEDLE_HEADER;
  if (dlen > 0) {
    put_be32(o + i, (uint32_t)dlen);
    i += 4;
    memcpy(o + i, data, dlen);
    i += dlen;
    o[i++] = flags;
    if (flags & FLAG_HAS_NAME) {
      o[i++] = (uint8_t)name.size();
      memcpy(o + i, name.data(), name.size());
      i += name.size();
    }
    if (flags & FLAG_HAS_MIME) {
      o[i++] = (uint8_t)mime.size();
      memcpy(o + i, mime.data(), mime.size());
      i += mime.size();
    }
    for (int k = 4; k >= 0; k--) o[i++] = (lm >> (8 * k)) & 0xFF;
  }
  put_be32(o + i, crc_masked(crc));
  i += 4;
  put_be64(o + i, ns);
  i += 8;
  // v3 padding quirk: first pad bytes alias [size u32BE, zeros]
  uint8_t pad_src[8] = {0};
  put_be32(pad_src, (uint32_t)size);
  memcpy(o + i, pad_src, pad);

  char js[96];
  {
    std::lock_guard<std::mutex> lk(vol->mu);
    if (vol->dead.load()) return 1;
    // volume cap scaled to the idx offset flavor: 32 GB for 4-byte offsets,
    // 8 EB-class for 5-byte (volume.py write_needle:326 checks content
    // bytes; the binding native invariant is offset representability)
    uint64_t cap = vol->max_offset();
    if (vol->file_bytes + (uint64_t)actual_size(size, 3) > cap ||
        vol->append_off + (uint64_t)rec_len > cap)
      return reply_json(w, c, 500,
                        "{\"error\": \"VolumeError: volume " +
                            std::to_string(f.vid) + " size limit exceeded\"}")
                 ? 0 : -1;
    Slot* s = vol->map.find(f.key);
    if (s && s->off != 0) {
      // existing needle: cookie check + unchanged check (write_needle:333-345)
      uint8_t hdr[NEEDLE_HEADER];
      if (pread(vol->dat_fd, hdr, NEEDLE_HEADER, s->off) == NEEDLE_HEADER) {
        if (be32(hdr) != f.cookie) {
          e->n_post++;
          char cb[16];
          snprintf(cb, sizeof(cb), "%x", f.cookie);
          return reply_json(w, c, 500,
                            "{\"error\": \"VolumeError: mismatching cookie " +
                                std::string(cb) + "\"}") ? 0 : -1;
        }
        if (s->size > 0 && s->size != TOMBSTONE) {
          // same data already stored? (volume.py _is_file_unchanged)
          int64_t old_rec = actual_size(s->size, vol->version);
          std::vector<uint8_t> oldb(old_rec);
          if (pread(vol->dat_fd, oldb.data(), old_rec, s->off) == old_rec) {
            ParsedNeedle op = parse_needle_record(oldb.data(), s->size,
                                                  vol->version);
            if (op.ok && op.data_len == dlen &&
                memcmp(op.data, data, dlen) == 0) {
              e->n_post++;
              snprintf(js, sizeof(js),
                       "{\"size\": %lld, \"eTag\": \"%08x\", \"unchanged\": true}",
                       (long long)dlen, crc);
              return reply_json(w, c, 201, js) ? 0 : -1;
            }
          }
        }
      }
    }
    uint64_t off = vol->append_off;
    if (pwrite(vol->dat_fd, rec.data(), rec_len, off) != rec_len)
      return reply_json(w, c, 500, "{\"error\": \"dat append failed\"}") ? 0 : -1;
    vol->append_off += rec_len;
    if (vol->write_idx_entry(f.key, off, (int32_t)size) != 0)
      return reply_json(w, c, 500, "{\"error\": \"idx append failed\"}") ? 0 : -1;
    vol->apply_put(f.key, off, (int32_t)size);
    vol->last_append_ns = ns;
    if (lm > vol->last_modified_s) vol->last_modified_s = lm;
    std::string fs = q_get(r.query, "fsync");
    if (fs == "true") {
      fsync(vol->dat_fd);
      fsync(vol->idx_fd);
    }
  }
  e->n_post++;
  snprintf(js, sizeof(js),
           "{\"size\": %lld, \"eTag\": \"%08x\", \"unchanged\": false}",
           (long long)dlen, crc);
  return reply_json(w, c, 201, js) ? 0 : -1;
}

// DELETE on a fid.  Returns: 0 handled, 1 proxy-me, -1 client dead.
static int handle_delete(Worker* w, Conn* c, const Req& r, const Fid& f) {
  Engine* e = w->eng;
  auto vol = e->get_vol(f.vid);
  if (!vol || vol->dead.load()) return 1;
  if (!auth_ok(e->jwt_write_key, r, f))
    return reply_json(w, c, 401, "{\"error\": \"unauthorized delete\"}")
               ? 0 : -1;
  if (!vol->writable_http || vol->version != 3) return 1;
  if (vol->read_only.load())
    return reply_json(w, c, 500,
                      "{\"error\": \"VolumeError: volume " +
                          std::to_string(f.vid) + " is read only\"}") ? 0 : -1;

  // peek flags first: chunk-manifest deletes cascade in Python
  {
    uint64_t off = 0;
    int32_t size = 0;
    {
      std::lock_guard<std::mutex> lk(vol->mu);
      if (vol->dead.load()) return 1;
      Slot* s = vol->map.find(f.key);
      if (!s || s->off == 0 || s->size <= 0 || s->size == TOMBSTONE) {
        e->n_delete++;
        return reply_json(w, c, 202, "{\"size\": 0}") ? 0 : -1;
      }
      off = s->off;
      size = s->size;
    }
    int64_t rec_len = actual_size(size, vol->version);
    std::vector<uint8_t> rec(rec_len);
    if (pread(vol->dat_fd, rec.data(), rec_len, off) == rec_len) {
      ParsedNeedle p = parse_needle_record(rec.data(), size, vol->version);
      if (p.ok && (p.flags & FLAG_IS_CHUNK_MANIFEST)) return 1;
    }
  }
  // tombstone: empty v3 needle (header + checksum + ts + pad = 32B)
  uint64_t ns = now_ns();
  int pad = padding_len(0, 3);
  int64_t rec_len = NEEDLE_HEADER + CHECKSUM_SIZE + TS_SIZE + pad;
  std::vector<uint8_t> rec(rec_len, 0);
  uint8_t* o = rec.data();
  put_be32(o, f.cookie);
  put_be64(o + 4, f.key);
  put_be32(o + 12, 0);
  put_be32(o + NEEDLE_HEADER, crc_masked(crc32c(nullptr, 0)));
  put_be64(o + NEEDLE_HEADER + 4, ns);
  // v3 pad aliases size bytes (all zero here) — already zeroed

  int32_t old_size = 0;
  {
    std::lock_guard<std::mutex> lk(vol->mu);
    if (vol->dead.load()) return 1;
    Slot* s = vol->map.find(f.key);
    if (!s || s->off == 0 || s->size <= 0 || s->size == TOMBSTONE) {
      e->n_delete++;
      return reply_json(w, c, 202, "{\"size\": 0}") ? 0 : -1;
    }
    old_size = s->size;
    uint64_t off = vol->append_off;
    if (pwrite(vol->dat_fd, rec.data(), rec_len, off) != rec_len)
      return reply_json(w, c, 500, "{\"error\": \"dat append failed\"}") ? 0 : -1;
    vol->append_off += rec_len;
    if (vol->write_idx_entry(f.key, off, TOMBSTONE) != 0)
      return reply_json(w, c, 500, "{\"error\": \"idx append failed\"}") ? 0 : -1;
    vol->apply_delete(f.key);
    vol->last_append_ns = ns;
  }
  e->n_delete++;
  char js[48];
  snprintf(js, sizeof(js), "{\"size\": %d}", old_size);
  return reply_json(w, c, 202, js) ? 0 : -1;
}

// ---------------------------------------------------------------------------
// Worker event loop.

enum HandleResult { H_OK = 0, H_DROP = 1, H_PROXY = 2 };

static HandleResult handle_one(Worker* w, Conn* c, const Req& r,
                               const std::string& raw) {
  bool is_get = ieq(r.method, r.method_len, "GET");
  bool is_head = ieq(r.method, r.method_len, "HEAD");
  bool is_post = ieq(r.method, r.method_len, "POST") ||
                 ieq(r.method, r.method_len, "PUT");
  bool is_del = ieq(r.method, r.method_len, "DELETE");

  Fid f;
  if (r.path.size() > 1 && isdigit((unsigned char)r.path[1]) &&
      parse_fid_path(r.path, &f)) {
    int rc;
    if (is_get || is_head)
      rc = handle_get(w, c, r, f, is_head);
    else if (is_post)
      rc = handle_post(w, c, r, f);
    else if (is_del)
      rc = handle_delete(w, c, r, f);
    else
      rc = 1;
    if (rc == 0) return H_OK;
    if (rc == -1) return H_DROP;
    // rc == 1: fall through to proxy
  }
  return H_PROXY;
}

static void close_conn(Worker* w, Conn* c) {
  epoll_ctl(w->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  w->conns.erase(c->fd);
  delete c;
}

// serve complete pipelined requests from c->in; true = keep connection
static bool process_requests(Worker* w, Conn* c) {
  while (c->out.empty()) {
    Req r{};
    int pr = parse_request(c->in, &r);
    if (pr == 0) return true;
    if (pr < 0) {
      reply_json(w, c, 400, "{\"error\": \"bad request\"}");
      return false;
    }
    c->close_after = r.conn_close;
    std::string raw = c->in.substr(0, r.total_len);
    Req r2{};  // re-parse against the stable copy (pointers into raw)
    if (parse_request(raw, &r2) != 1) return false;
    c->in.erase(0, r.total_len);
    HandleResult hr = handle_one(w, c, r2, raw);
    if (hr == H_DROP) return false;
    if (hr == H_PROXY) {
      // hand the connection to a proxy thread; the epoll loop forgets the
      // fd until the completion queue returns it (re-entrant backend
      // requests to this port keep being served meanwhile)
      epoll_ctl(w->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
      w->conns.erase(c->fd);
      w->inflight++;
      Engine* e = w->eng;
      bool is_head = ieq(r2.method, r2.method_len, "HEAD");
      std::thread([w, e, c, raw, is_head] {
        bool ok = proxy_blocking(e, c->fd, raw, is_head);
        {
          std::lock_guard<std::mutex> lk(w->done_mu);
          w->done.emplace_back(c, ok && !c->close_after);
        }
        uint64_t one = 1;
        (void)!write(w->notify_fd, &one, 8);
      }).detach();
      return true;  // conn ownership transferred
    }
    if (c->close_after) return c->out.empty() ? false : true;
  }
  return true;
}

static void worker_loop(Worker* w) {
  epoll_event evs[128];
  char rbuf[262144];
  while (!w->eng->stopping.load()) {
    int n = epoll_wait(w->epfd, evs, 128, 1000);
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == w->stop_fd) {
        uint64_t v;
        (void)!read(w->stop_fd, &v, 8);
        continue;
      }
      if (fd == w->notify_fd) {
        uint64_t v;
        (void)!read(w->notify_fd, &v, 8);
        std::vector<std::pair<Conn*, bool>> done;
        {
          std::lock_guard<std::mutex> lk(w->done_mu);
          done.swap(w->done);
        }
        for (auto& [c, ok] : done) {
          w->inflight--;
          if (!ok) {
            close(c->fd);
            delete c;
            continue;
          }
          w->conns[c->fd] = c;
          epoll_event ev{};
          // EPOLLOUT fires immediately on a writable socket, so leftover
          // pipelined requests in c->in get processed promptly
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.fd = c->fd;
          epoll_ctl(w->epfd, EPOLL_CTL_ADD, c->fd, &ev);
        }
        continue;
      }
      if (fd == w->listen_fd) {
        while (true) {
          int cfd = accept4(w->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn{cfd};
          w->conns[cfd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(w->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      auto it = w->conns.find(fd);
      if (it == w->conns.end()) continue;
      Conn* c = it->second;
      bool drop = false;
      bool transferred = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(w, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        while (!c->out.empty()) {
          ssize_t sn = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
          if (sn > 0) {
            c->out.erase(0, sn);
            continue;
          }
          if (sn < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
          drop = true;
          break;
        }
        if (!drop && c->out.empty()) {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = c->fd;
          epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          if (c->close_after) drop = true;
        }
      }
      if (!drop && (evs[i].events & EPOLLIN)) {
        size_t pass_start = c->in.size();
        while (true) {
          ssize_t rn = recv(fd, rbuf, sizeof(rbuf), 0);
          if (rn > 0) {
            c->in.append(rbuf, rn);
            // backstop matching parse_request's MAX_BODY: body cap + header
            // slack; a conn can never legitimately buffer more than this
            if (c->in.size() > ((size_t)1 << 30) + (17 << 20)) {
              drop = true;
              break;
            }
            // read at most 4 MB per pass so process_requests gets to
            // reject bogus framing (oversize Content-Length, unterminated
            // headers) early — a fast sender must not be able to keep this
            // loop spinning until the gigabyte backstop; level-triggered
            // epoll re-fires for the rest
            if (c->in.size() - pass_start > (4u << 20)) break;
            continue;
          }
          if (rn == 0) {
            drop = true;  // peer closed
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          drop = true;
          break;
        }
      }
      if (!drop) {
        size_t before = w->conns.count(fd);
        bool keep = process_requests(w, c);
        transferred = before && !w->conns.count(fd);  // proxy took it
        if (!transferred && !keep) drop = true;
      }
      if (drop && !transferred) close_conn(w, c);
    }
  }
  // teardown: wait for proxy threads still holding our Conn pointers.
  // Completions queued after the loop exited must be drained HERE (the
  // notify handler no longer runs) or inflight never reaches zero and
  // turbo_stop deadlocks. Bounded: a proxy thread wedged on a dead
  // backend is abandoned (conn leaked) rather than hanging shutdown.
  for (int spins = 0; w->inflight.load() > 0 && spins < 1500; spins++) {
    std::vector<std::pair<Conn*, bool>> done;
    {
      std::lock_guard<std::mutex> lk(w->done_mu);
      done.swap(w->done);
    }
    for (auto& [c, ok] : done) {
      w->inflight--;
      close(c->fd);
      delete c;
    }
    if (w->inflight.load() > 0) usleep(10000);
  }
  if (w->inflight.load() > 0) w->leak.store(true);
  {
    std::lock_guard<std::mutex> lk(w->done_mu);
    for (auto& [c, ok] : w->done) {
      w->inflight--;
      close(c->fd);
      delete c;
    }
    w->done.clear();
  }
  for (auto& kv : w->conns) {
    close(kv.first);
    delete kv.second;
  }
  w->conns.clear();
  if (w->listen_fd >= 0) close(w->listen_fd);
  // stop_fd is NOT closed here: turbo_stop may still be fanning the wake
  // write out to other workers' stop_fds — closing ours concurrently
  // races that write (and a recycled fd number would take the 8-byte wake
  // into an unrelated file). The engine owns stop_fds and closes them
  // after joining every worker (turbo_stop).
  // a leaked worker keeps notify_fd open: the wedged proxy thread will
  // still write it, and the fd number must not be recycled under it
  if (w->notify_fd >= 0 && !w->leak.load()) close(w->notify_fd);
  if (w->epfd >= 0) close(w->epfd);
}

// ---------------------------------------------------------------------------
// C API.

extern "C" {

// returns engine handle (opaque pointer) or 0 on failure
long long turbo_start(const char* bind_ip, int port, const char* backend_ip,
                      int backend_port, int threads) {
  static std::once_flag once;
  std::call_once(once, [] {
    crc_init_tables();
#if defined(__x86_64__)
    g_has_sse42 = __builtin_cpu_supports("sse4.2");
#endif
    signal(SIGPIPE, SIG_IGN);
  });
  if (threads < 1) threads = 1;
  if (threads > 16) threads = 16;
  Engine* e = new Engine();
  e->bind_ip = bind_ip ? bind_ip : "";
  e->port = port;
  e->backend_ip = backend_ip ? backend_ip : "127.0.0.1";
  e->backend_port = backend_port;
  std::vector<Worker*> ws;
  for (int t = 0; t < threads; t++) {
    Worker* w = new Worker();
    w->eng = e;
    w->listen_fd = make_listener(e->bind_ip.c_str(), port);
    if (w->listen_fd < 0) {
      delete w;
      for (Worker* pw : ws) {
        close(pw->listen_fd);
        close(pw->stop_fd);
        close(pw->epfd);
        delete pw;
      }
      delete e;
      return 0;
    }
    w->epfd = epoll_create1(0);
    w->stop_fd = eventfd(0, EFD_NONBLOCK);
    w->notify_fd = eventfd(0, EFD_NONBLOCK);
    for (int lfd : {w->listen_fd, w->stop_fd, w->notify_fd}) {
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = lfd;
      epoll_ctl(w->epfd, EPOLL_CTL_ADD, lfd, &ev);
    }
    ws.push_back(w);
  }
  for (Worker* w : ws) {
    e->stop_fds.push_back(w->stop_fd);
    e->workers.emplace_back([w] {
      worker_loop(w);
      if (!w->leak.load()) delete w;  // leaked workers outlive wedged proxies
    });
  }
  return (long long)(intptr_t)e;
}

// Install fid-JWT keys. Call BEFORE volumes are registered (keys are read
// without locks on the hot path; the engine serves only proxied traffic
// until registration anyway).
void turbo_set_jwt(long long handle, const char* write_key,
                   const char* read_key) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return;
  e->jwt_write_key = write_key ? write_key : "";
  e->jwt_read_key = read_key ? read_key : "";
}

void turbo_stop(long long handle) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return;
  e->stopping.store(true);
  for (int fd : e->stop_fds) {
    uint64_t one = 1;
    (void)!write(fd, &one, 8);
  }
  for (auto& t : e->workers) t.join();
  for (int fd : e->stop_fds) close(fd);  // workers joined: safe to close
  {
    std::unique_lock<std::shared_mutex> lk(e->reg_mu);
    e->vols.clear();
  }
  delete e;
}

// 0 ok; -1 io error; -2 already registered; -3 bad idx
int turbo_register(long long handle, unsigned vid, const char* dat_path,
                   const char* idx_path, int version, int offset_size,
                   int writable_http, int read_only) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  {
    std::shared_lock<std::shared_mutex> lk(e->reg_mu);
    if (e->vols.count(vid)) return -2;
  }
  auto v = std::make_shared<Vol>();
  v->vid = vid;
  v->version = version;
  v->offset_size = offset_size;
  v->writable_http = writable_http != 0;
  v->read_only.store(read_only != 0);
  v->dat_fd = open(dat_path, O_RDWR);
  v->idx_fd = open(idx_path, O_RDWR);
  if (v->dat_fd < 0 || v->idx_fd < 0) return -1;
  struct stat st;
  if (fstat(v->dat_fd, &st) != 0) return -1;
  v->append_off = st.st_size;
  if (fstat(v->idx_fd, &st) != 0) return -1;
  v->idx_size = st.st_size;
  // replay the .idx with CompactNeedleMap.load semantics
  int es = v->entry_size();
  uint64_t healthy = v->idx_size - (v->idx_size % es);
  std::vector<uint8_t> buf(1 << 20);
  uint64_t pos = 0;
  while (pos < healthy) {
    size_t chunk = std::min<uint64_t>(buf.size() - (buf.size() % es),
                                      healthy - pos);
    ssize_t got = pread(v->idx_fd, buf.data(), chunk, pos);
    if (got != (ssize_t)chunk) return -3;
    for (size_t i = 0; i + es <= chunk; i += es) {
      const uint8_t* p = buf.data() + i;
      uint64_t key = be64(p);
      uint64_t scaled = be32(p + 8);
      const uint8_t* szp = p + 12;
      if (offset_size == 5) {
        scaled |= (uint64_t)p[12] << 32;
        szp = p + 13;
      }
      uint64_t off = scaled * PAD;
      int32_t size = (int32_t)be32(szp);
      if (key == EMPTY_KEY) return -3;  // sentinel collision: stay in Python
      if (key > v->max_key) v->max_key = key;  // load counts deletes too
      if (off != 0 && size > 0 && size != TOMBSTONE)
        v->apply_put(key, off, size);
      else
        v->apply_delete(key);
    }
    pos += chunk;
  }
  std::unique_lock<std::shared_mutex> lk(e->reg_mu);
  if (e->vols.count(vid)) return -2;
  e->vols[vid] = v;
  return 0;
}

int turbo_unregister(long long handle, unsigned vid) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  std::shared_ptr<Vol> v;
  {
    std::unique_lock<std::shared_mutex> lk(e->reg_mu);
    auto it = e->vols.find(vid);
    if (it == e->vols.end()) return -2;
    v = it->second;
    e->vols.erase(it);
  }
  {
    // wait for the in-flight op (if any) and fence future ones
    std::lock_guard<std::mutex> lk(v->mu);
    v->dead.store(true);
  }
  return 0;
}

int turbo_lookup(long long handle, unsigned vid, unsigned long long key,
                 unsigned long long* off, int* size) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  auto v = e->get_vol(vid);
  if (!v) return -2;
  std::lock_guard<std::mutex> lk(v->mu);
  Slot* s = v->map.find(key);
  if (!s) return 0;
  *off = s->off;
  *size = s->size;
  return 1;
}

// Append a fully-built record (Python writes exotic needles through here).
// is_delete: record is a tombstone; size_field is the idx entry size value.
int turbo_append(long long handle, unsigned vid, unsigned long long key,
                 const unsigned char* rec, unsigned long long rec_len,
                 int size_field, int is_delete, unsigned long long* out_off) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  if (key == EMPTY_KEY) return -5;  // needle-map slot sentinel; unstorable
  auto v = e->get_vol(vid);
  if (!v) return -2;
  std::lock_guard<std::mutex> lk(v->mu);
  if (v->dead.load()) return -2;
  uint64_t off = v->append_off;
  if (off > v->max_offset()) return -4;  // unrepresentable in this idx flavor
  if (pwrite(v->dat_fd, rec, rec_len, off) != (ssize_t)rec_len) return -1;
  v->append_off += rec_len;
  if (is_delete) {
    if (v->write_idx_entry(key, off, TOMBSTONE) != 0) return -1;
    v->apply_delete(key);
  } else {
    if (v->write_idx_entry(key, off, size_field) != 0) return -1;
    v->apply_put(key, off, size_field);
  }
  if (rec_len >= NEEDLE_HEADER + CHECKSUM_SIZE + TS_SIZE &&
      v->version == 3) {
    // trailer timestamp sits before padding; recover it for stats
    int32_t nsize = is_delete ? 0 : size_field;
    int64_t ts_off = NEEDLE_HEADER + nsize + CHECKSUM_SIZE;
    if ((uint64_t)(ts_off + TS_SIZE) <= rec_len)
      v->last_append_ns = be64(rec + ts_off);
  }
  *out_off = off;
  return 0;
}

// out[9]: file_count, file_bytes, del_count, del_bytes, max_key,
//         dat_size, idx_size, last_modified_s, last_append_ns
int turbo_stats(long long handle, unsigned vid, unsigned long long* out) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  auto v = e->get_vol(vid);
  if (!v) return -2;
  std::lock_guard<std::mutex> lk(v->mu);
  out[0] = v->file_count;
  out[1] = v->file_bytes;
  out[2] = v->del_count;
  out[3] = v->del_bytes;
  out[4] = v->max_key;
  out[5] = v->append_off;
  out[6] = v->idx_size;
  out[7] = v->last_modified_s;
  out[8] = v->last_append_ns;
  return 0;
}

int turbo_set_readonly(long long handle, unsigned vid, int ro) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  auto v = e->get_vol(vid);
  if (!v) return -2;
  v->read_only.store(ro != 0);
  return 0;
}

int turbo_sync(long long handle, unsigned vid) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return -1;
  auto v = e->get_vol(vid);
  if (!v) return -2;
  std::lock_guard<std::mutex> lk(v->mu);
  fsync(v->dat_fd);
  fsync(v->idx_fd);
  return 0;
}

// out[4]: native gets, posts, deletes, proxied
void turbo_counters(long long handle, unsigned long long* out) {
  Engine* e = (Engine*)(intptr_t)handle;
  if (!e) return;
  out[0] = e->n_get.load();
  out[1] = e->n_post.load();
  out[2] = e->n_delete.load();
  out[3] = e->n_proxy.load();
}

}  // extern "C"
