// sweed_native: host-side kernels for seaweedfs_tpu.
//
// The reference leans on native SIMD in its dependencies (klauspost/reedsolomon
// amd64 assembly for GF(2^8), hardware CRC32 in the Go stdlib). This library is
// our host equivalent: a portable C++ Reed-Solomon matmul over GF(2^8) (poly
// 0x11D, klauspost-compatible) used as the CPU fallback + cross-check oracle
// for the TPU codec, and CRC-32C (Castagnoli, slicing-by-8) for needle
// checksums (weed/storage/needle/crc.go).
//
// Build: make -C seaweedfs_tpu/native   (g++ -O3 -shared -fPIC)
// ABI: plain C functions, consumed via ctypes.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>
#if defined(__AVX2__) || defined(__GFNI__)
#include <immintrin.h>  // outside extern "C": intrinsics need C++ linkage
#endif

// The GFNI tier needs 512-bit vectors, byte masks and the affine op.
#if defined(__GFNI__) && defined(__AVX512F__) && defined(__AVX512BW__)
#define SWEED_GFNI 1
#endif

namespace {

// ---------------- GF(2^8), poly 0x11D ----------------
constexpr uint32_t kPoly = 0x11D;

struct GfTables {
  uint8_t exp[512];
  int32_t log[256];
  // mul[a][b] lazily derived via log/exp in rs_matmul setup
  GfTables() {
    uint32_t x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= kPoly;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    log[0] = -1;
  }
  uint8_t mul(uint8_t a, uint8_t b) const {
    if (a == 0 || b == 0) return 0;
    return exp[log[a] + log[b]];
  }
};

const GfTables& gf() {
  static GfTables t;
  return t;
}

// ---------------- CRC-32C slicing-by-8 ----------------
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    constexpr uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t crc = i;
      for (int k = 0; k < 8; k++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
      t[0][i] = crc;
    }
    for (int k = 1; k < 8; k++)
      for (uint32_t i = 0; i < 256; i++)
        t[k][i] = (t[k - 1][i] >> 8) ^ t[0][t[k - 1][i] & 0xFF];
  }
};

const CrcTables& crc_tables() {
  static CrcTables t;
  return t;
}

}  // namespace

extern "C" {

uint32_t sweed_crc32c_update(uint32_t crc, const uint8_t* data, size_t n) {
  const CrcTables& ct = crc_tables();
  crc ^= 0xFFFFFFFFu;
  size_t i = 0;
  while (n - i >= 8) {
    uint32_t lo;
    std::memcpy(&lo, data + i, 4);
    crc ^= lo;  // little-endian host assumed (x86/arm64)
    crc = ct.t[7][crc & 0xFF] ^ ct.t[6][(crc >> 8) & 0xFF] ^
          ct.t[5][(crc >> 16) & 0xFF] ^ ct.t[4][(crc >> 24) & 0xFF] ^
          ct.t[3][data[i + 4]] ^ ct.t[2][data[i + 5]] ^
          ct.t[1][data[i + 6]] ^ ct.t[0][data[i + 7]];
    i += 8;
  }
  for (; i < n; i++) crc = (crc >> 8) ^ ct.t[0][(crc ^ data[i]) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// out[r*n .. r*n+n) = XOR over k of matrix[r*kk + c] * in[c*n .. c*n+n)
// over GF(2^8). in: (kk, n) row-major contiguous; out: (out_rows, n).
// Skip-list support for reconstruct: if in_present != nullptr, column c of the
// matrix applies to input row c only when in_present[c] != 0, and matrix
// columns are indexed by input-slot (so callers pass a full-width matrix with
// zeros for absent slots or compact inputs — we use compact inputs here).
#if defined(__AVX2__)
// One coefficient's contribution over n bytes, 32 at a time: the PSHUFB
// nibble-table kernel (klauspost's galois_amd64.s formulation — two 16-entry
// product tables indexed by the low/high nibble of every input byte).
static inline void mul_xor_avx2(const uint8_t* src, uint8_t* dst, size_t n,
                                const uint8_t lo[16], const uint8_t hi[16],
                                bool first) {
  const __m256i lot =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo));
  const __m256i hit =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  size_t j = 0;
  for (; j + 32 <= n; j += 32) {
    __m256i v = _mm256_loadu_si256((const __m256i*)(src + j));
    __m256i l = _mm256_and_si256(v, mask);
    __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
    __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lot, l),
                                 _mm256_shuffle_epi8(hit, h));
    if (!first)
      r = _mm256_xor_si256(r, _mm256_loadu_si256((const __m256i*)(dst + j)));
    _mm256_storeu_si256((__m256i*)(dst + j), r);
  }
  for (; j < n; j++) {
    uint8_t v = src[j];
    uint8_t x = lo[v & 0x0F] ^ hi[v >> 4];
    dst[j] = first ? x : (uint8_t)(dst[j] ^ x);
  }
}
#endif

// ---------------- coefficient prep + blocked matmul ----------------
//
// Two ingredients lift this from ~1.1 GB/s to klauspost's class:
//
//  * per-matrix PREP: every coefficient's multiply representation (GFNI
//    affine qword, or lo/hi PSHUFB nibble tables) is derived once and
//    reused — the old loop rederived the tables on every call for every
//    (r, c) pair. Python callers cache the prep blob per matrix.
//  * COLUMN BLOCKING: the r-outer/c-inner loop streamed every input row
//    from DRAM once per OUTPUT row (~(k+1)·rows memory passes — 154 B of
//    traffic per input byte for a full RS(10,4) shard set). Processing
//    64 KB column blocks keeps the whole (k + rows)-row working set in L2,
//    so DRAM traffic drops to read-input + write-output.

}  // extern "C"

namespace {

constexpr size_t kColBlock = 64 * 1024;  // (k + rows) · 64 KB fits a 1–2 MB L2

#if defined(SWEED_GFNI)
constexpr size_t kPrepStride = 8;  // one VGF2P8AFFINEQB bit-matrix qword

// Multiplication by a constant is GF(2)-linear, so it is an 8×8 bit matrix:
// column j is mul(coef, 1<<j). VGF2P8AFFINEQB keeps the row for output bit b
// in byte (7 - b) of the qword.
uint64_t affine_qword(uint8_t coef) {
  const GfTables& g = gf();
  uint64_t m = 0;
  for (int b = 0; b < 8; b++) {
    uint8_t row = 0;
    for (int j = 0; j < 8; j++)
      row |= static_cast<uint8_t>(
          ((g.mul(coef, static_cast<uint8_t>(1u << j)) >> b) & 1) << j);
    m |= static_cast<uint64_t>(row) << (8 * (7 - b));
  }
  return m;
}

void prep_coef(uint8_t coef, uint8_t* entry) {
  uint64_t q = affine_qword(coef);
  std::memcpy(entry, &q, 8);
}

inline bool prep_is_zero(const uint8_t* entry) {
  uint64_t q;
  std::memcpy(&q, entry, 8);
  return q == 0;
}

// Register-accumulator matmul: walk 256-byte column strips; per strip, row
// groups of ≤4 keep 4×4 zmm accumulators live across the whole c loop, so
// every output byte is STORED exactly once and never re-loaded, and every
// input strip is read once from DRAM (row groups after the first hit L1).
// This is klauspost's mulAvx512GFNI loop shape (galois_gen_amd64.s).
inline void gfni_strip(const uint8_t* prep, int out_rows, int kk, size_t n,
                       const uint8_t* in, uint8_t* out, size_t j,
                       __mmask64 tail_mask[4], int nv) {
  for (int r0 = 0; r0 < out_rows; r0 += 4) {
    const int rg = (out_rows - r0 < 4) ? out_rows - r0 : 4;
    __m512i acc[4][4];
    for (int rr = 0; rr < rg; rr++)
      for (int i = 0; i < 4; i++) acc[rr][i] = _mm512_setzero_si512();
    for (int c = 0; c < kk; c++) {
      const uint8_t* src = in + static_cast<size_t>(c) * n + j;
      if (!tail_mask && r0 == 0) {
        // 10+ round-robined input streams starve the hardware prefetcher
        // (measured 2.5→7.5 GB/s on one core); pull the strip 2 KB ahead.
        _mm_prefetch(reinterpret_cast<const char*>(src + 2048), _MM_HINT_T0);
        _mm_prefetch(reinterpret_cast<const char*>(src + 2048 + 64), _MM_HINT_T0);
        _mm_prefetch(reinterpret_cast<const char*>(src + 2048 + 128), _MM_HINT_T0);
        _mm_prefetch(reinterpret_cast<const char*>(src + 2048 + 192), _MM_HINT_T0);
      }
      __m512i v[4];
      for (int i = 0; i < nv; i++)
        v[i] = tail_mask ? _mm512_maskz_loadu_epi8(tail_mask[i], src + 64 * i)
                         : _mm512_loadu_si512(src + 64 * i);
      for (int rr = 0; rr < rg; rr++) {
        uint64_t q;
        std::memcpy(&q, prep + (static_cast<size_t>(r0 + rr) * kk + c) * 8, 8);
        if (q == 0) continue;
        const __m512i A = _mm512_set1_epi64(static_cast<long long>(q));
        for (int i = 0; i < nv; i++)
          acc[rr][i] = _mm512_xor_si512(
              acc[rr][i], _mm512_gf2p8affine_epi64_epi8(v[i], A, 0));
      }
    }
    for (int rr = 0; rr < rg; rr++) {
      uint8_t* dst = out + static_cast<size_t>(r0 + rr) * n + j;
      for (int i = 0; i < nv; i++) {
        if (tail_mask)
          _mm512_mask_storeu_epi8(dst + 64 * i, tail_mask[i], acc[rr][i]);
        else if ((reinterpret_cast<uintptr_t>(dst) & 63) == 0)
          // written once, never read back: NT store skips the RFO read
          _mm512_stream_si512(reinterpret_cast<__m512i*>(dst + 64 * i),
                              acc[rr][i]);
        else
          _mm512_storeu_si512(dst + 64 * i, acc[rr][i]);
      }
    }
  }
}

void matmul_prep_impl(const uint8_t* prep, int out_rows, int kk, size_t n,
                      const uint8_t* in, uint8_t* out) {
  size_t j = 0;
  for (; j + 256 <= n; j += 256)
    gfni_strip(prep, out_rows, kk, n, in, out, j, nullptr, 4);
  if (j < n) {
    const size_t rem = n - j;
    __mmask64 masks[4];
    int nv = 0;
    for (size_t off = 0; off < rem; off += 64, nv++)
      masks[nv] = (rem - off >= 64) ? ~0ULL : ((~0ULL) >> (64 - (rem - off)));
    gfni_strip(prep, out_rows, kk, n, in, out, j, masks, nv);
  }
  _mm_sfence();  // drain the NT store buffers before the caller reads out
}

#else  // PSHUFB / scalar tiers share the lo/hi nibble-table prep

constexpr size_t kPrepStride = 32;  // lo[16] | hi[16] product tables

void prep_coef(uint8_t coef, uint8_t* entry) {
  const GfTables& g = gf();
  for (int x = 0; x < 16; x++) {
    entry[x] = g.mul(coef, static_cast<uint8_t>(x));
    entry[16 + x] = g.mul(coef, static_cast<uint8_t>(x << 4));
  }
}

inline bool prep_is_zero(const uint8_t* entry) {
  return entry[1] == 0;  // lo[1] == mul(coef, 1) == coef
}

inline void mul_xor_block(const uint8_t* src, uint8_t* dst, size_t n,
                          const uint8_t* entry, bool first) {
#if defined(__AVX2__)
  mul_xor_avx2(src, dst, n, entry, entry + 16, first);
#else
  const uint8_t* lo = entry;
  const uint8_t* hi = entry + 16;
  if (first) {
    for (size_t j = 0; j < n; j++) {
      uint8_t v = src[j];
      dst[j] = lo[v & 0x0F] ^ hi[v >> 4];
    }
  } else {
    for (size_t j = 0; j < n; j++) {
      uint8_t v = src[j];
      dst[j] ^= lo[v & 0x0F] ^ hi[v >> 4];
    }
  }
#endif
}

void matmul_prep_impl(const uint8_t* prep, int out_rows, int kk, size_t n,
                      const uint8_t* in, uint8_t* out) {
  for (size_t pos = 0; pos < n; pos += kColBlock) {
    const size_t bn = (n - pos < kColBlock) ? n - pos : kColBlock;
    for (int r = 0; r < out_rows; r++) {
      uint8_t* dst = out + static_cast<size_t>(r) * n + pos;
      bool first = true;
      for (int c = 0; c < kk; c++) {
        const uint8_t* entry =
            prep + (static_cast<size_t>(r) * kk + c) * kPrepStride;
        if (prep_is_zero(entry)) continue;
        mul_xor_block(in + static_cast<size_t>(c) * n + pos, dst, bn, entry,
                      first);
        first = false;
      }
      if (first) std::memset(dst, 0, bn);  // all-zero matrix row
    }
  }
}

#endif  // SWEED_GFNI

}  // namespace

extern "C" {

size_t sweed_rs_prep_bytes(void) { return kPrepStride; }

// Derive the per-coefficient multiply prep for a whole (out_rows × kk)
// matrix into `prep` (out_rows*kk*sweed_rs_prep_bytes() bytes). Callers
// cache the blob per matrix and feed it to sweed_rs_matmul_prep.
void sweed_rs_prep(const uint8_t* matrix, int out_rows, int kk,
                   uint8_t* prep) {
  for (int i = 0; i < out_rows * kk; i++)
    prep_coef(matrix[i], prep + static_cast<size_t>(i) * kPrepStride);
}

void sweed_rs_matmul_prep(const uint8_t* prep, int out_rows, int kk, size_t n,
                          const uint8_t* in, uint8_t* out) {
  matmul_prep_impl(prep, out_rows, kk, n, in, out);
}

void sweed_rs_matmul(const uint8_t* matrix, int out_rows, int kk, size_t n,
                     const uint8_t* in, uint8_t* out) {
  std::vector<uint8_t> prep(static_cast<size_t>(out_rows) * kk * kPrepStride);
  sweed_rs_prep(matrix, out_rows, kk, prep.data());
  sweed_rs_matmul_prep(prep.data(), out_rows, kk, n, in, out);
}

// XOR n bytes of src into dst (helper for journal/parity delta paths).
void sweed_xor_bytes(uint8_t* dst, const uint8_t* src, size_t n) {
  for (size_t j = 0; j < n; j++) dst[j] ^= src[j];
}

// Which rs_matmul inner loop this build compiled in — benches record it so
// a published CPU-fallback number can never silently come from the wrong
// kernel (the r4 artifact had 0.028 GB/s with no way to tell why).
const char* sweed_kernel_variant(void) {
#if defined(SWEED_GFNI)
  return "gfni";  // VGF2P8AFFINEQB constant-multiply, 64 B/op
#elif defined(__AVX2__)
  return "avx2";
#else
  return "scalar";
#endif
}

}  // extern "C"
