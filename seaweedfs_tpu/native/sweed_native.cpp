// sweed_native: host-side kernels for seaweedfs_tpu.
//
// The reference leans on native SIMD in its dependencies (klauspost/reedsolomon
// amd64 assembly for GF(2^8), hardware CRC32 in the Go stdlib). This library is
// our host equivalent: a portable C++ Reed-Solomon matmul over GF(2^8) (poly
// 0x11D, klauspost-compatible) used as the CPU fallback + cross-check oracle
// for the TPU codec, and CRC-32C (Castagnoli, slicing-by-8) for needle
// checksums (weed/storage/needle/crc.go).
//
// Build: make -C seaweedfs_tpu/native   (g++ -O3 -shared -fPIC)
// ABI: plain C functions, consumed via ctypes.

#include <cstdint>
#include <cstring>
#include <cstddef>
#if defined(__AVX2__)
#include <immintrin.h>  // outside extern "C": intrinsics need C++ linkage
#endif

namespace {

// ---------------- GF(2^8), poly 0x11D ----------------
constexpr uint32_t kPoly = 0x11D;

struct GfTables {
  uint8_t exp[512];
  int32_t log[256];
  // mul[a][b] lazily derived via log/exp in rs_matmul setup
  GfTables() {
    uint32_t x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= kPoly;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    log[0] = -1;
  }
  uint8_t mul(uint8_t a, uint8_t b) const {
    if (a == 0 || b == 0) return 0;
    return exp[log[a] + log[b]];
  }
};

const GfTables& gf() {
  static GfTables t;
  return t;
}

// ---------------- CRC-32C slicing-by-8 ----------------
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    constexpr uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t crc = i;
      for (int k = 0; k < 8; k++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
      t[0][i] = crc;
    }
    for (int k = 1; k < 8; k++)
      for (uint32_t i = 0; i < 256; i++)
        t[k][i] = (t[k - 1][i] >> 8) ^ t[0][t[k - 1][i] & 0xFF];
  }
};

const CrcTables& crc_tables() {
  static CrcTables t;
  return t;
}

}  // namespace

extern "C" {

uint32_t sweed_crc32c_update(uint32_t crc, const uint8_t* data, size_t n) {
  const CrcTables& ct = crc_tables();
  crc ^= 0xFFFFFFFFu;
  size_t i = 0;
  while (n - i >= 8) {
    uint32_t lo;
    std::memcpy(&lo, data + i, 4);
    crc ^= lo;  // little-endian host assumed (x86/arm64)
    crc = ct.t[7][crc & 0xFF] ^ ct.t[6][(crc >> 8) & 0xFF] ^
          ct.t[5][(crc >> 16) & 0xFF] ^ ct.t[4][(crc >> 24) & 0xFF] ^
          ct.t[3][data[i + 4]] ^ ct.t[2][data[i + 5]] ^
          ct.t[1][data[i + 6]] ^ ct.t[0][data[i + 7]];
    i += 8;
  }
  for (; i < n; i++) crc = (crc >> 8) ^ ct.t[0][(crc ^ data[i]) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// out[r*n .. r*n+n) = XOR over k of matrix[r*kk + c] * in[c*n .. c*n+n)
// over GF(2^8). in: (kk, n) row-major contiguous; out: (out_rows, n).
// Skip-list support for reconstruct: if in_present != nullptr, column c of the
// matrix applies to input row c only when in_present[c] != 0, and matrix
// columns are indexed by input-slot (so callers pass a full-width matrix with
// zeros for absent slots or compact inputs — we use compact inputs here).
#if defined(__AVX2__)
// One coefficient's contribution over n bytes, 32 at a time: the PSHUFB
// nibble-table kernel (klauspost's galois_amd64.s formulation — two 16-entry
// product tables indexed by the low/high nibble of every input byte).
static inline void mul_xor_avx2(const uint8_t* src, uint8_t* dst, size_t n,
                                const uint8_t lo[16], const uint8_t hi[16],
                                bool first) {
  const __m256i lot =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo));
  const __m256i hit =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  size_t j = 0;
  for (; j + 32 <= n; j += 32) {
    __m256i v = _mm256_loadu_si256((const __m256i*)(src + j));
    __m256i l = _mm256_and_si256(v, mask);
    __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
    __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lot, l),
                                 _mm256_shuffle_epi8(hit, h));
    if (!first)
      r = _mm256_xor_si256(r, _mm256_loadu_si256((const __m256i*)(dst + j)));
    _mm256_storeu_si256((__m256i*)(dst + j), r);
  }
  for (; j < n; j++) {
    uint8_t v = src[j];
    uint8_t x = lo[v & 0x0F] ^ hi[v >> 4];
    dst[j] = first ? x : (uint8_t)(dst[j] ^ x);
  }
}
#endif

void sweed_rs_matmul(const uint8_t* matrix, int out_rows, int kk, size_t n,
                     const uint8_t* in, uint8_t* out) {
  const GfTables& g = gf();
  // Per (r, c) coefficient, two 16-entry nibble tables: with AVX2 the inner
  // loop is klauspost's PSHUFB kernel (32 bytes per shuffle pair); without,
  // the scalar table-lookup cousin.
  for (int r = 0; r < out_rows; r++) {
    uint8_t* dst = out + static_cast<size_t>(r) * n;
    bool first = true;
    for (int c = 0; c < kk; c++) {
      uint8_t coef = matrix[r * kk + c];
      const uint8_t* src = in + static_cast<size_t>(c) * n;
      if (coef == 0) {
        if (first) std::memset(dst, 0, n);
        // note: klauspost also zero-fills then XORs; zero coef contributes 0
        first = first && true;
        continue;
      }
      uint8_t lo[16], hi[16];
      for (int x = 0; x < 16; x++) {
        lo[x] = g.mul(coef, static_cast<uint8_t>(x));
        hi[x] = g.mul(coef, static_cast<uint8_t>(x << 4));
      }
#if defined(__AVX2__)
      mul_xor_avx2(src, dst, n, lo, hi, first);
      first = false;
#else
      if (first) {
        for (size_t j = 0; j < n; j++) {
          uint8_t v = src[j];
          dst[j] = lo[v & 0x0F] ^ hi[v >> 4];
        }
        first = false;
      } else {
        for (size_t j = 0; j < n; j++) {
          uint8_t v = src[j];
          dst[j] ^= lo[v & 0x0F] ^ hi[v >> 4];
        }
      }
#endif
    }
    if (first) std::memset(dst, 0, n);  // all-zero matrix row
  }
}

// XOR n bytes of src into dst (helper for journal/parity delta paths).
void sweed_xor_bytes(uint8_t* dst, const uint8_t* src, size_t n) {
  for (size_t j = 0; j < n; j++) dst[j] ^= src[j];
}

// Which rs_matmul inner loop this build compiled in — benches record it so
// a published CPU-fallback number can never silently come from the wrong
// kernel (the r4 artifact had 0.028 GB/s with no way to tell why).
const char* sweed_kernel_variant(void) {
#if defined(__AVX2__)
  return "avx2";
#else
  return "scalar";
#endif
}

}  // extern "C"
