// ThreadSanitizer stress harness for the turbo engine (SURVEY §5.2 asks
// for TSan-equivalent coverage where native code exists).
//
// Links turbo.cpp directly and exercises every concurrency seam at once:
// epoll workers serving HTTP GET/POST/DELETE, the Python-delegation C API
// (turbo_append / turbo_lookup) racing the HTTP writers on the same
// volume, stats/counters/sync readers, and a readonly-flag toggler. Any
// data race TSan sees makes the process exit non-zero (default TSan
// exitcode=66), which tests/test_tsan.py treats as failure.
//
// Build: make -C seaweedfs_tpu/native tsan   → ./tsan_harness <workdir>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

extern "C" {
long long turbo_start(const char* bind_ip, int port, const char* backend_ip,
                      int backend_port, int threads);
void turbo_stop(long long handle);
void turbo_set_jwt(long long handle, const char* wk, const char* rk);
int turbo_register(long long handle, unsigned vid, const char* dat_path,
                   const char* idx_path, int version, int offset_size,
                   int writable_http, int read_only);
int turbo_append(long long handle, unsigned vid, unsigned long long key,
                 const unsigned char* rec, unsigned long long rec_len,
                 int size_field, int is_delete, unsigned long long* out_off);
int turbo_lookup(long long handle, unsigned vid, unsigned long long key,
                 unsigned long long* off, int* size);
int turbo_stats(long long handle, unsigned vid, unsigned long long* out);
int turbo_sync(long long handle, unsigned vid);
int turbo_set_readonly(long long handle, unsigned vid, int ro);
void turbo_counters(long long handle, unsigned long long* out);
}

namespace {

int http_roundtrip(int port, const std::string& req) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  inet_pton(AF_INET, "127.0.0.1", &a.sin_addr);
  timeval tv{2, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  if (connect(fd, (sockaddr*)&a, sizeof(a)) < 0) {
    close(fd);
    return -1;
  }
  (void)!send(fd, req.data(), req.size(), MSG_NOSIGNAL);
  char buf[4096];
  ssize_t n = recv(fd, buf, sizeof(buf), 0);
  close(fd);
  if (n < 12) return -1;
  return (buf[9] - '0') * 100 + (buf[10] - '0') * 10 + (buf[11] - '0');
}

// one valid v3 needle record for turbo_append (cookie|key|size|data|crc|ts|pad)
std::vector<uint8_t> make_record(uint64_t key, uint32_t size_field) {
  // header 16 + [4B dlen + data + 1B flags] + crc4 + ts8 + pad→8
  uint32_t dlen = size_field - 5;  // size = 4 + dlen + 1 for plain data
  size_t body = size_field;
  size_t raw = 16 + body + 4 + 8;
  size_t padded = (raw + 7) & ~size_t(7);
  std::vector<uint8_t> r(padded, 0);
  auto be32 = [&](size_t off, uint32_t v) {
    r[off] = v >> 24; r[off + 1] = v >> 16; r[off + 2] = v >> 8; r[off + 3] = v;
  };
  auto be64 = [&](size_t off, uint64_t v) {
    for (int i = 0; i < 8; i++) r[off + i] = v >> (56 - 8 * i);
  };
  be32(0, 0xC00C1Eu);       // cookie
  be64(4, key);
  be32(12, size_field);
  be32(16, dlen);
  for (uint32_t i = 0; i < dlen; i++) r[20 + i] = (uint8_t)(key + i);
  r[20 + dlen] = 0;          // flags
  // crc over data bytes — harness uses 0; readers through the C API don't
  // verify, and HTTP readers only read HTTP-written needles
  be64(16 + body + 4, 1234567890ull);  // timestamp ns
  return r;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: tsan_harness <workdir>\n");
    return 2;
  }
  std::string dir = argv[1];
  std::string dat = dir + "/1.dat", idx = dir + "/1.idx";
  FILE* f = fopen(dat.c_str(), "wb");
  if (!f) {
    fprintf(stderr, "cannot create %s\n", dat.c_str());
    return 2;
  }
  uint8_t sb[8] = {3, 0, 0, 0, 0, 0, 0, 0};  // v3 superblock
  fwrite(sb, 1, 8, f);
  fclose(f);
  FILE* fi = fopen(idx.c_str(), "wb");
  if (!fi) {
    fprintf(stderr, "cannot create %s\n", idx.c_str());
    return 2;
  }
  fclose(fi);

  long long h = 0;
  int port = 0;
  std::mt19937 seed_rng(12345);
  for (int attempt = 0; attempt < 20 && !h; attempt++) {
    port = 20000 + (int)(seed_rng() % 20000);
    h = turbo_start("127.0.0.1", port, "127.0.0.1", 1, 2);  // 2 workers
  }
  if (!h) {
    fprintf(stderr, "turbo_start failed\n");
    return 2;
  }
  if (turbo_register(h, 1, dat.c_str(), idx.c_str(), 3, 4, 1, 0) != 0) {
    fprintf(stderr, "turbo_register failed\n");
    return 2;
  }

  std::atomic<bool> stop{false};
  std::atomic<int> http_posts{0}, http_gets{0}, api_appends{0};
  std::vector<std::thread> ts;

  // HTTP writers (distinct key ranges per thread)
  for (int t = 0; t < 3; t++) {
    ts.emplace_back([&, t] {
      std::mt19937 rng(t);
      int i = 0;
      while (!stop.load()) {
        char path[64];
        snprintf(path, sizeof(path), "/1,%xdeadbeef",
                 0x1000 * (t + 1) + (i++ % 512));
        std::string body(64 + rng() % 512, 'x');
        std::string req = std::string("POST ") + path +
                          " HTTP/1.1\r\nHost: x\r\nContent-Length: " +
                          std::to_string(body.size()) + "\r\n\r\n" + body;
        if (http_roundtrip(port, req) == 201) http_posts++;
      }
    });
  }
  // HTTP readers
  for (int t = 0; t < 3; t++) {
    ts.emplace_back([&, t] {
      std::mt19937 rng(100 + t);
      while (!stop.load()) {
        char path[64];
        snprintf(path, sizeof(path), "/1,%xdeadbeef",
                 (unsigned)(0x1000 * (1 + rng() % 3) + rng() % 512));
        std::string req = std::string("GET ") + path +
                          " HTTP/1.1\r\nHost: x\r\n\r\n";
        int st = http_roundtrip(port, req);
        if (st == 200 || st == 404) http_gets++;
      }
    });
  }
  // C-API appender + lookups (the Python-delegation seam) on its own keys
  ts.emplace_back([&] {
    uint64_t key = 0x900000;
    while (!stop.load()) {
      auto rec = make_record(key, 64);
      unsigned long long off = 0;
      if (turbo_append(h, 1, key, rec.data(), rec.size(), 64, 0, &off) == 0)
        api_appends++;
      unsigned long long o;
      int sz;
      turbo_lookup(h, 1, key - (key % 7), &o, &sz);
      key++;
    }
  });
  // stats / counters / sync reader
  ts.emplace_back([&] {
    while (!stop.load()) {
      unsigned long long st9[9], c4[4];
      turbo_stats(h, 1, st9);
      turbo_counters(h, c4);
      turbo_sync(h, 1);
    }
  });
  // readonly toggler (writers then see 500s; flag races are the point)
  ts.emplace_back([&] {
    int i = 0;
    while (!stop.load()) {
      turbo_set_readonly(h, 1, (i++ % 8) == 7 ? 1 : 0);
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
  });

  std::this_thread::sleep_for(std::chrono::seconds(3));
  stop.store(true);
  for (auto& t : ts) t.join();
  turbo_stop(h);
  fprintf(stderr, "harness done: posts=%d gets=%d api_appends=%d\n",
          http_posts.load(), http_gets.load(), api_appends.load());
  if (http_posts.load() < 50 || http_gets.load() < 50 ||
      api_appends.load() < 50) {
    fprintf(stderr, "too little traffic exercised\n");
    return 3;
  }
  return 0;
}
