"""ctypes bindings for the native turbo data plane (_sweed_turbo.so).

`TurboEngine` wraps one native engine instance (epoll HTTP workers on the
volume server's public port + the per-volume needle state).  While a volume
is attached, the native engine is the single writer of its .dat/.idx; the
Python `Volume` delegates through `TurboNeedleMap` (lookups, counters) and
`TurboEngine.append` (exotic writes that the native HTTP fast path proxies
back to Python: TTL'd needles, replicated fan-out, manifest cascades).

See native/turbo.cpp for the ownership protocol; the reference analog is the
compiled Go data plane in weed/server/volume_server_handlers_{read,write}.go.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from ..storage.needle_map import NeedleMapper, NeedleValue
from ..storage.types import OFFSET_SIZE, TOMBSTONE_FILE_SIZE
from ..util import glog

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "turbo.cpp")
_SO = os.path.join(_DIR, "build", "_sweed_turbo.so")

_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if (not os.path.exists(_SO)) or (
            os.path.exists(_SRC)  # prebuilt-.so-only deployments load as-is
            and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            subprocess.run(
                ["make", "-C", _DIR, "-s", "build/_sweed_turbo.so"],
                check=True, capture_output=True, timeout=180,
            )
        lib = ctypes.CDLL(_SO)
        lib.turbo_start.restype = ctypes.c_longlong
        lib.turbo_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.turbo_stop.argtypes = [ctypes.c_longlong]
        lib.turbo_set_jwt.argtypes = [
            ctypes.c_longlong, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.turbo_register.restype = ctypes.c_int
        lib.turbo_register.argtypes = [
            ctypes.c_longlong, ctypes.c_uint, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.turbo_unregister.restype = ctypes.c_int
        lib.turbo_unregister.argtypes = [ctypes.c_longlong, ctypes.c_uint]
        lib.turbo_lookup.restype = ctypes.c_int
        lib.turbo_lookup.argtypes = [
            ctypes.c_longlong, ctypes.c_uint, ctypes.c_ulonglong,
            ctypes.POINTER(ctypes.c_ulonglong), ctypes.POINTER(ctypes.c_int),
        ]
        lib.turbo_append.restype = ctypes.c_int
        lib.turbo_append.argtypes = [
            ctypes.c_longlong, ctypes.c_uint, ctypes.c_ulonglong,
            ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ulonglong),
        ]
        lib.turbo_stats.restype = ctypes.c_int
        lib.turbo_stats.argtypes = [
            ctypes.c_longlong, ctypes.c_uint,
            ctypes.POINTER(ctypes.c_ulonglong),
        ]
        lib.turbo_set_readonly.restype = ctypes.c_int
        lib.turbo_set_readonly.argtypes = [
            ctypes.c_longlong, ctypes.c_uint, ctypes.c_int,
        ]
        lib.turbo_sync.restype = ctypes.c_int
        lib.turbo_sync.argtypes = [ctypes.c_longlong, ctypes.c_uint]
        lib.turbo_counters.argtypes = [
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_ulonglong),
        ]
        _lib = lib
    except Exception as e:  # noqa: BLE001 — any failure = Python fallback
        glog.warning("turbo engine unavailable: %s", e)
        _load_failed = True
        _lib = None
    return _lib


def turbo_available() -> bool:
    return _load() is not None


class TurboEngine:
    """One native engine instance: HTTP workers + attached volumes."""

    def __init__(self, bind_ip: str, port: int, backend_ip: str,
                 backend_port: int, threads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native turbo library unavailable")
        if threads <= 0:
            threads = int(os.environ.get("SWEED_TURBO_THREADS", "0") or 0)
        if threads <= 0:
            threads = min(4, max(1, (os.cpu_count() or 1) - 1)) if (
                os.cpu_count() or 1) > 1 else 1
        self._lib = lib
        self._h = lib.turbo_start(
            bind_ip.encode(), port, backend_ip.encode(), backend_port, threads
        )
        if not self._h:
            raise RuntimeError(f"turbo_start failed to bind {bind_ip}:{port}")
        self.port = port
        self.threads = threads

    def set_jwt_keys(self, write_key: str, read_key: str) -> None:
        """Install fid-JWT keys for native verification (call before any
        volume is attached; security/jwt.py semantics)."""
        self._lib.turbo_set_jwt(self._h, write_key.encode(),
                                read_key.encode())

    def stop(self) -> None:
        if self._h:
            self._lib.turbo_stop(self._h)
            self._h = 0

    # -- volume attach/detach ------------------------------------------------
    def register(self, vid: int, dat_path: str, idx_path: str, version: int,
                 offset_size: int, writable_http: bool, read_only: bool) -> bool:
        rc = self._lib.turbo_register(
            self._h, vid, dat_path.encode(), idx_path.encode(), version,
            offset_size, 1 if writable_http else 0, 1 if read_only else 0,
        )
        if rc != 0:
            glog.V(1).info("turbo register vid %d failed rc=%d", vid, rc)
        return rc == 0

    def unregister(self, vid: int) -> bool:
        return self._lib.turbo_unregister(self._h, vid) == 0

    # -- delegated needle-map ops -------------------------------------------
    def lookup(self, vid: int, key: int) -> Optional[tuple[int, int]]:
        off = ctypes.c_ulonglong()
        size = ctypes.c_int()
        rc = self._lib.turbo_lookup(self._h, vid, key, ctypes.byref(off),
                                    ctypes.byref(size))
        if rc == 1:
            return off.value, size.value
        if rc == 0:
            return None
        raise KeyError(f"volume {vid} not attached to turbo")

    def append(self, vid: int, key: int, record: bytes, size_field: int,
               is_delete: bool) -> int:
        out = ctypes.c_ulonglong()
        rc = self._lib.turbo_append(
            self._h, vid, key, record, len(record), size_field,
            1 if is_delete else 0, ctypes.byref(out),
        )
        if rc != 0:
            raise OSError(f"turbo_append vid {vid} failed rc={rc}")
        return out.value

    def stats(self, vid: int) -> dict:
        buf = (ctypes.c_ulonglong * 9)()
        rc = self._lib.turbo_stats(self._h, vid, buf)
        if rc != 0:
            raise KeyError(f"volume {vid} not attached to turbo")
        return {
            "file_count": buf[0], "file_bytes": buf[1],
            "del_count": buf[2], "del_bytes": buf[3],
            "max_key": buf[4], "dat_size": buf[5], "idx_size": buf[6],
            "last_modified_s": buf[7], "last_append_ns": buf[8],
        }

    def set_readonly(self, vid: int, ro: bool) -> None:
        self._lib.turbo_set_readonly(self._h, vid, 1 if ro else 0)

    def sync(self, vid: int) -> None:
        self._lib.turbo_sync(self._h, vid)

    def counters(self) -> dict:
        buf = (ctypes.c_ulonglong * 4)()
        self._lib.turbo_counters(self._h, buf)
        return {"gets": buf[0], "posts": buf[1], "deletes": buf[2],
                "proxied": buf[3]}


class TurboNeedleMap(NeedleMapper):
    """NeedleMapper view over the native engine's per-volume state.

    Installed by Volume.attach_turbo; mutations must NOT come through here
    (the Volume routes them through TurboEngine.append so the .dat append,
    .idx entry, and map update stay atomic under the native lock)."""

    def __init__(self, engine: TurboEngine, vid: int, index_file,
                 offset_size: int = OFFSET_SIZE):
        self.engine = engine
        self.vid = vid
        self._index_file = index_file  # kept for detach-time reload
        self._offset_size = offset_size

    def get(self, key: int) -> Optional[NeedleValue]:
        hit = self.engine.lookup(self.vid, key)
        if hit is None:
            return None
        return NeedleValue(key, hit[0], hit[1])

    def put(self, key: int, offset: int, size: int) -> None:
        raise RuntimeError("turbo volume: put must go through Volume.write_needle")

    def delete(self, key: int, offset: int) -> None:
        raise RuntimeError("turbo volume: delete must go through Volume.delete_needle")

    def ascending_visit(self, fn) -> None:
        # rare admin path (needle listing): replay the on-disk .idx, which
        # the native engine keeps current per append
        from ..storage import idx as idx_mod
        from ..storage.types import size_is_valid

        live: dict[int, tuple[int, int]] = {}
        with open(self._index_file.name, "rb") as f:
            for key, off, size in idx_mod.iter_index_file(f, self._offset_size):
                if size_is_valid(size):
                    live[key] = (off, size)
                else:
                    old = live.get(key)
                    if old is not None:
                        live[key] = (old[0], -abs(old[1]))
        for key in sorted(live):
            off, size = live[key]
            fn(NeedleValue(key, off, size))

    # -- counters (mapMetric parity) ----------------------------------------
    def _s(self) -> dict:
        return self.engine.stats(self.vid)

    def content_size(self) -> int:
        return self._s()["file_bytes"]

    def deleted_size(self) -> int:
        return self._s()["del_bytes"]

    def file_count(self) -> int:
        return self._s()["file_count"]

    def deleted_count(self) -> int:
        return self._s()["del_count"]

    @property
    def max_file_key(self) -> int:
        return self._s()["max_key"]

    def index_file_size(self) -> int:
        return self._s()["idx_size"]

    def sync(self) -> None:
        self.engine.sync(self.vid)

    def release(self) -> None:
        pass

    def close(self) -> None:
        # engine detach closes native fds; the shared python handle is
        # closed by the Volume on full close
        pass
