"""Subtree-ownership reshard: move a directory subtree between filers
so ring-membership changes converge, surviving a crash at ANY step.

This is the PR-10 replication discipline (filer_sync's
``repl.applied.<sig>.<ts>.<hash>`` idempotence markers + durable offset
checkpoints) re-aimed at metadata migration:

1. deterministic DFS (preorder, children sorted by name — the store's
   listing order) over the subtree on the SOURCE filer;
2. per-entry idempotence marker ``reshard.applied.<epoch>.<sha1(path)>``
   written to the TARGET's KV *after* the entry lands there — a replayed
   apply sees the marker and skips, so a crashed run re-driven from the
   top never duplicates an entry;
3. a durable prefix checkpoint (every ``ckpt_every`` applies) recording
   the last applied path, so resumption skips whole already-copied
   subtrees without even paying the marker round-trips;
4. a ``done`` marker once the copy is complete — the purge below never
   runs before it, so a crash window can leave the subtree on both
   filers (harmless: ring ownership already points at the target) but
   never on neither;
5. metadata-only purge of the source subtree (``skipChunkPurge`` — the
   chunks on volume servers are shared by both copies; fids never
   change, which is why resharding is cheap);
6. marker GC by walking the TARGET subtree (markers are only ever
   written for entries that exist there, so the walk enumerates them
   exactly), then dropping checkpoint and done marker.

Faultpoints (``reshard.apply``, ``reshard.checkpoint``,
``reshard.done``, ``reshard.purge``) arm the kill windows the chaos
matrix drives: kill the filer at each, restart, re-drive the reshard,
and the tree hash must converge with zero dupes or drops.
"""

from __future__ import annotations

import hashlib
import urllib.parse
from typing import Optional

from ..util import faultpoints, glog
from .client import FilerClient


def _sha1(path: str) -> str:
    return hashlib.sha1(path.encode()).hexdigest()


class _InternalFilerClient(FilerClient):
    """FilerClient whose every request carries ``noRedirect=1``: reshard
    traffic must hit the addressed filer itself — the source still holds
    entries the ring says belong elsewhere, and the target receives
    entries before it would pass its own ownership check."""

    def _u(self, path: str, **q) -> str:
        q.setdefault("noRedirect", "1")
        qs = urllib.parse.urlencode({k: v for k, v in q.items() if v != ""})
        return self.base + urllib.parse.quote(path) + ("?" + qs if qs else "")


class Resharder:
    """One subtree move, re-drivable until it reports done."""

    def __init__(self, source_url: str, target_url: str, root: str,
                 epoch: str, ckpt_every: int = 32):
        self.root = "/" + root.strip("/")
        self.epoch = str(epoch)
        self.src = _InternalFilerClient(source_url, retry_reads=True)
        self.dst = _InternalFilerClient(target_url, retry_reads=True)
        self.ckpt_every = max(1, ckpt_every)
        self._since_ckpt = 0
        self.applied = 0
        self.marker_skips = 0
        self.ckpt_skips = 0
        self.resumed_from = ""

    # marker / checkpoint keys ------------------------------------------------
    def _mkey(self, path: str) -> str:
        return f"reshard.applied.{self.epoch}.{_sha1(path)}"

    @property
    def _ckpt_key(self) -> str:
        return f"reshard.ckpt.{self.epoch}.{_sha1(self.root)}"

    @property
    def _done_key(self) -> str:
        return f"reshard.done.{self.epoch}.{_sha1(self.root)}"

    # protocol ----------------------------------------------------------------
    def run(self) -> dict:
        """Drive the move to completion from whatever state a previous
        (possibly killed) run left behind."""
        ckpt = self.dst.kv_get(self._ckpt_key)
        self.resumed_from = ckpt.decode() if ckpt else ""
        if self.dst.kv_get(self._done_key) is None:
            root_entry = self.src.get_entry(self.root)
            if root_entry is None:
                # source subtree already purged by a prior run that died
                # between purge and GC — nothing to copy
                glog.info("reshard %s: source empty, copy already complete",
                          self.root)
            else:
                self._apply(self.root, root_entry)
                self._walk(self.root)
            self.dst.kv_put(self._done_key, b"1")
            faultpoints.fire("reshard.done")
        # copy durable; everything below is idempotent cleanup
        self.src.delete(self.root, recursive=True, skip_chunk_purge=True)
        faultpoints.fire("reshard.purge")
        self._gc_markers()
        return {
            "root": self.root, "epoch": self.epoch,
            "applied": self.applied, "marker_skips": self.marker_skips,
            "ckpt_skips": self.ckpt_skips, "resumed_from": self.resumed_from,
        }

    def _walk(self, dir_path: str) -> None:
        cursor = ""
        while True:
            page = self.src.list(dir_path, start_after=cursor, limit=1000)
            if not page:
                break
            for e in page:
                cursor = e["name"]
                path = f"{dir_path.rstrip('/')}/{e['name']}"
                if self._skip_by_ckpt(path):
                    self.ckpt_skips += 1
                    continue
                if not self._is_ckpt_ancestor(path):
                    self._apply(path, e)
                if e.get("is_directory"):
                    self._walk(path)
            if len(page) < 1000:
                break

    def _skip_by_ckpt(self, path: str) -> bool:
        """True when the checkpoint proves ``path`` AND its whole subtree
        are already applied. In preorder-with-sorted-children, a subtree
        occupies a contiguous path-string range: if ``path`` sorts before
        the checkpoint and the checkpoint is NOT inside the subtree, then
        every subtree path sorts before the checkpoint too."""
        ck = self.resumed_from
        return bool(ck) and path < ck and not ck.startswith(path + "/")

    def _is_ckpt_ancestor(self, path: str) -> bool:
        """Ancestors of the checkpoint path were applied before it was
        written (preorder); recurse into them but skip the re-apply."""
        ck = self.resumed_from
        return bool(ck) and (path == ck or ck.startswith(path + "/"))

    def _apply(self, path: str, entry: dict) -> None:
        key = self._mkey(path)
        if self.dst.kv_get(key) is not None:
            self.marker_skips += 1
            return
        entry = dict(entry)
        entry["full_path"] = path
        entry.pop("name", None)
        self.dst.create_entry(path, entry)
        # marker AFTER the entry: a crash between them re-applies the
        # same bytes (idempotent), the reverse order could drop the entry
        self.dst.kv_put(key, b"1")
        faultpoints.fire("reshard.apply", path=path)
        self.applied += 1
        self._since_ckpt += 1
        if self._since_ckpt >= self.ckpt_every:
            self._since_ckpt = 0
            self.dst.kv_put(self._ckpt_key, path.encode())
            faultpoints.fire("reshard.checkpoint")

    def _gc_markers(self) -> None:
        """Markers exist only for entries present on the target, so a
        target-side walk enumerates every one; the done marker goes last
        so a crash mid-GC resumes as idempotent cleanup."""
        stack = [self.root]
        while stack:
            d = stack.pop()
            self.dst.kv_delete(self._mkey(d))
            e = self.dst.get_entry(d)
            if e is None or not e.get("is_directory"):
                continue
            cursor = ""
            while True:
                page = self.dst.list(d, start_after=cursor, limit=1000)
                if not page:
                    break
                for c in page:
                    cursor = c["name"]
                    child = f"{d.rstrip('/')}/{c['name']}"
                    if c.get("is_directory"):
                        stack.append(child)
                    else:
                        self.dst.kv_delete(self._mkey(child))
                if len(page) < 1000:
                    break
        self.dst.kv_delete(self._ckpt_key)
        self.dst.kv_delete(self._done_key)


def tree_hash(filer_url: str, root: str) -> str:
    """Order-independent content hash of a subtree's metadata, computed
    through the addressed filer (noRedirect, so fleet members can be
    hashed individually). Two filers agree iff they hold byte-identical
    trees — the chaos matrix's convergence oracle."""
    c = _InternalFilerClient(filer_url, retry_reads=True)
    h = hashlib.sha256()
    stack = ["/" + root.strip("/")]
    lines = []
    while stack:
        d = stack.pop()
        cursor = ""
        while True:
            page = c.list(d, start_after=cursor, limit=1000)
            if not page:
                break
            for e in page:
                cursor = e["name"]
                path = f"{d.rstrip('/')}/{e['name']}"
                if e.get("is_directory"):
                    lines.append(f"D {path}")
                    stack.append(path)
                else:
                    chunks = ",".join(
                        f"{ch.get('file_id', '')}@{ch.get('offset', 0)}+{ch.get('size', 0)}"
                        for ch in e.get("chunks", []))
                    lines.append(f"F {path} {chunks}")
            if len(page) < 1000:
                break
    for line in sorted(lines):
        h.update(line.encode() + b"\n")
    return h.hexdigest()
