"""Redis-protocol FilerStore — networked metadata store.

Mirrors `weed/filer/redis2/universal_redis_store.go`: each entry is a
string value at its full path; each directory keeps a sorted set
(`<dir>\\x00`) of child names so listings page lexicographically
(ZRANGEBYLEX, which also gives exclusive start-after semantics for free).
KV checkpoints ride the same keyspace under a binary prefix.

The wire client is a dependency-free RESP2 implementation over stdlib
sockets — any redis/valkey-compatible server works, including the
in-package `util.mini_redis` stand-in used by tests.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Iterator, Optional

from ..util.resp import BufferedRespReader
from .entry import Entry
from .filerstore import FilerStore, NotFoundError, _norm

DIR_LIST_SUFFIX = b"\x00"
KV_PREFIX = b"\x01kv\x01"


class RespError(RuntimeError):
    pass


class RespClient:
    """Minimal RESP2 client: encode command arrays, parse replies."""

    def __init__(
        self,
        address: str = "127.0.0.1:6379",
        password: str = "",
        database: int = 0,
        timeout: float = 10.0,
    ):
        if ":" in address:
            host, _, port_s = address.rpartition(":")
            port = int(port_s)
        else:
            host, port = address, 6379  # bare hostname: default redis port
        self._sock = socket.create_connection(
            (host or "127.0.0.1", port), timeout=timeout
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = BufferedRespReader(lambda: self._sock.recv(65536))
        self._lock = threading.Lock()
        if password:
            self.execute("AUTH", password)
        if database:
            self.execute("SELECT", str(database))

    # -- wire ---------------------------------------------------------------
    @staticmethod
    def _enc(args: tuple) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, str):
                a = a.encode()
            elif isinstance(a, (int, float)):
                a = str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _read_reply(self):
        line = self._reader.read_line()
        if line is None:
            raise RespError("connection closed")
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            out = self._reader.read_exact(n)
            if out is None:
                raise RespError("connection closed")
            return out
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self._read_reply() for _ in range(n)]
        raise RespError(f"bad reply type {line!r}")

    def execute(self, *args):
        with self._lock:
            self._sock.sendall(self._enc(args))
            return self._read_reply()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class RedisStore(FilerStore):
    def __init__(
        self,
        address: str = "127.0.0.1:6379",
        password: str = "",
        database: int = 0,
    ):
        self._client = RespClient(address, password=password, database=database)

    # -- keys ---------------------------------------------------------------
    @staticmethod
    def _dir_list_key(dir_path: str) -> bytes:
        return _norm(dir_path).encode() + DIR_LIST_SUFFIX

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        path = _norm(path)
        if path == "/":
            return "", ""
        d, _, name = path.rpartition("/")
        return d or "/", name

    # -- entries ------------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        path = _norm(entry.full_path)
        value = json.dumps(entry.to_dict()).encode()
        args = ["SET", path, value]
        ttl = getattr(entry, "ttl_sec", 0)
        if ttl:
            args += ["EX", ttl]
        self._client.execute(*args)
        d, name = self._split(path)
        if name:
            self._client.execute("ZADD", self._dir_list_key(d), 0, name)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        data = self._client.execute("GET", _norm(path))
        if data is None:
            raise NotFoundError(path)
        return Entry.from_dict(json.loads(data))

    def delete_entry(self, path: str) -> None:
        path = _norm(path)
        self._client.execute("DEL", path, path.encode() + DIR_LIST_SUFFIX)
        d, name = self._split(path)
        if name:
            self._client.execute("ZREM", self._dir_list_key(d), name)

    def delete_folder_children(self, path: str) -> None:
        key = self._dir_list_key(path)
        children = self._client.execute("ZRANGE", key, 0, -1) or []
        base = _norm(path)
        for name in children:
            child = (base.rstrip("/") + "/" + name.decode())
            # recurse: a child with its own dir-list set is a directory
            if self._client.execute(
                "EXISTS", child.encode() + DIR_LIST_SUFFIX
            ):
                self.delete_folder_children(child)
            self._client.execute(
                "DEL", child, child.encode() + DIR_LIST_SUFFIX
            )
        self._client.execute("DEL", key)

    def list_entries(
        self, dir_path: str, start_after: str = "", limit: int = 1000
    ) -> Iterator[Entry]:
        key = self._dir_list_key(dir_path)
        lo = b"(" + start_after.encode() if start_after else b"-"
        names = (
            self._client.execute(
                "ZRANGEBYLEX", key, lo, b"+", "LIMIT", 0, limit
            )
            or []
        )
        base = _norm(dir_path).rstrip("/")
        for name in names:
            try:
                yield self.find_entry(f"{base}/{name.decode()}")
            except NotFoundError:
                # entry expired / deleted out-of-band: drop the stale member
                self._client.execute("ZREM", key, name)

    # -- kv -----------------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes) -> None:
        self._client.execute("SET", KV_PREFIX + key, value)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._client.execute("GET", KV_PREFIX + key)

    def kv_delete(self, key: bytes) -> None:
        self._client.execute("DEL", KV_PREFIX + key)

    def close(self) -> None:
        self._client.close()
