"""Path-prefix storage rules (FilerConf).

Reference: `weed/filer/filer_conf.go` — a config entry stored INSIDE the
filer at `/etc/seaweedfs/filer.conf` holds per-path-prefix storage
options (collection, replication, ttl, fsync); the longest matching
prefix wins. The reference stores protobuf text; this build stores JSON:

    {"locations": [
        {"location_prefix": "/buckets/media/", "collection": "media",
         "replication": "010", "ttl": "30d", "fsync": false}
    ]}
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

DIR_ETC = "/etc/seaweedfs"
FILER_CONF_NAME = "filer.conf"
FILER_CONF_PATH = f"{DIR_ETC}/{FILER_CONF_NAME}"


@dataclass
class PathConf:
    location_prefix: str = ""
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    fsync: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "PathConf":
        return cls(
            location_prefix=d.get("location_prefix", ""),
            collection=d.get("collection", ""),
            replication=d.get("replication", ""),
            ttl=d.get("ttl", ""),
            fsync=bool(d.get("fsync", False)),
        )


@dataclass
class FilerConf:
    locations: list[PathConf] = field(default_factory=list)

    @classmethod
    def from_bytes(cls, data: bytes) -> "FilerConf":
        try:
            doc = json.loads(data or b"{}")
        except json.JSONDecodeError:
            return cls()
        return cls(
            locations=[PathConf.from_dict(d) for d in doc.get("locations", [])]
        )

    def match_storage_rule(self, path: str) -> PathConf:
        """Longest matching location_prefix wins (filer_conf.go MatchStorageRule)."""
        best = PathConf()
        for rule in self.locations:
            if rule.location_prefix and path.startswith(rule.location_prefix):
                if len(rule.location_prefix) > len(best.location_prefix):
                    best = rule
        return best

    # -- editing (fs.configure / command_fs_configure.go) --------------------
    def set_rule(
        self,
        location_prefix: str,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
        fsync: bool = False,
    ) -> None:
        """Upsert the rule for a prefix (AddLocationConf semantics)."""
        self.delete_prefix(location_prefix)
        self.locations.append(
            PathConf(
                location_prefix=location_prefix,
                collection=collection,
                replication=replication,
                ttl=ttl,
                fsync=fsync,
            )
        )

    def delete_prefix(self, location_prefix: str) -> None:
        self.locations = [
            r for r in self.locations if r.location_prefix != location_prefix
        ]

    def to_dict(self) -> dict:
        return {
            "locations": [
                {
                    "location_prefix": r.location_prefix,
                    "collection": r.collection,
                    "replication": r.replication,
                    "ttl": r.ttl,
                    "fsync": r.fsync,
                }
                for r in self.locations
            ]
        }

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_dict(), indent=2).encode()
