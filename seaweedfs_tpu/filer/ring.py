"""Path-prefix sharding of the directory tree across a filer fleet.

One filer's store bounds the whole cluster's metadata throughput. The
scale-out mirrors the reference's path-prefix partitioning discussions
(`weed/filer` + stathat-style consistent hashing already proven by
messaging/broker): the tree is split by the first ``SWEED_RING_DEPTH``
path segments (default 2 — ``/bucket/toplevel``), and each shard key
maps onto one filer via :class:`~..messaging.consistent.ConsistentRing`.
Everything below a shard root lives on that shard's filer, so a
subtree's metadata ops never cross filers.

Two kinds of path, two placement rules:

- **shard paths** (>= depth segments): owned by exactly one filer —
  ``owner(path)`` = ring.get(shard key). The whole subtree under a shard
  root shares its key, so recursive ops stay single-filer.
- **spine dirs** (< depth segments, e.g. ``/`` and ``/bucket``): exist on
  EVERY filer. Spine listings fan out to all members and merge; spine
  mkdir/delete fan out too. This keeps ``ls /bucket`` correct without a
  directory-location service.

Ring placement is a pure function of the member set (consistent.py is
hardened for exactly this), so every daemon and client computes identical
ownership from the same ``ring_peers`` list — no coordination service.

:class:`RingFilerClient` is the smart client: same surface as
:class:`~.client.FilerClient`, but routes each call to the owner and
fans out spine ops. Dumb clients keep working because an owning filer
answers reads for foreign paths with ``307 Location:`` (and proxies
writes) — see filer_server; plain FilerClient follows the redirect.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ..messaging.consistent import ConsistentRing
from .client import FilerClient, FilerHTTPError


def ring_depth() -> int:
    """How many leading path segments form the shard key."""
    raw = os.environ.get("SWEED_RING_DEPTH", "2").strip()
    if not (raw.isascii() and raw.isdigit()) or int(raw) < 1:
        return 2
    return int(raw)


def _segments(path: str) -> list[str]:
    return [s for s in path.strip("/").split("/") if s]


def shard_key(path: str, depth: Optional[int] = None) -> str:
    """The ring key for ``path``: its first ``depth`` segments (fewer if
    the path is shorter). ``/`` maps to itself."""
    depth = depth if depth is not None else ring_depth()
    segs = _segments(path)
    if not segs:
        return "/"
    return "/" + "/".join(segs[:depth])


class FilerRing:
    """Ownership map for one fleet: ``members`` are filer addresses
    (``host:port``). A <2-member ring is inert — every path is owned
    locally and no redirects/fan-out happen, which is what keeps the
    single-filer tier-1 world byte-identical."""

    def __init__(self, members: list[str], self_url: str = "",
                 depth: Optional[int] = None):
        self.depth = depth if depth is not None else ring_depth()
        self.self_url = self_url
        self._ring = ConsistentRing()
        seen = set()
        for m in members:
            m = m.strip()
            if m and m not in seen:
                seen.add(m)
                self._ring.add(m)

    @property
    def active(self) -> bool:
        return len(self._ring) > 1

    def members(self) -> list[str]:
        return self._ring.members()

    def is_spine(self, path: str) -> bool:
        """Spine dirs (< depth segments) exist on every filer."""
        return len(_segments(path)) < self.depth

    def owner(self, path: str) -> str:
        if not self.active:
            return self.self_url
        return self._ring.get(shard_key(path, self.depth))

    def owns(self, path: str) -> bool:
        """Does THIS filer serve ``path``? Spine paths: everyone does."""
        if not self.active or self.is_spine(path):
            return True
        return self.owner(path) == self.self_url

    def plan(self) -> dict:
        """Shard layout for /_ring introspection and reshard planning."""
        return {
            "depth": self.depth,
            "members": self.members(),
            "self": self.self_url,
            "active": self.active,
        }


class RingFilerClient:
    """Drop-in for FilerClient that routes by ring ownership.

    Single-path ops go straight to the owner (no redirect hop); spine
    listings fan out to every member and merge by name; spine
    mkdir/delete fan out. Gateways (client/fs.py, s3api) construct this
    when handed multiple filer addresses and keep their code unchanged —
    the surface is FilerClient's."""

    def __init__(self, filer_urls: list[str], retry_reads: bool = True,
                 depth: Optional[int] = None,
                 client_factory: Callable[..., FilerClient] = FilerClient):
        if not filer_urls:
            raise ValueError("RingFilerClient needs at least one filer")
        self.ring = FilerRing(filer_urls, self_url=filer_urls[0], depth=depth)
        self._clients = {
            u: client_factory(u, retry_reads=retry_reads)
            for u in self.ring.members()
        }
        # non-path ops (assign/status/kv/meta_events) pin to one home
        # filer so sequences like kv_put → kv_get stay on one store
        self._home = self._clients[self.ring.members()[0]]
        self.base = self._home.base

    def _c(self, path: str) -> FilerClient:
        return self._clients[self.ring.owner(path)]

    def _u(self, path: str, **q) -> str:
        """Owner-routed URL for ``path`` — gateways' zero-copy fast paths
        build raw filer URLs (s3api native GET) and must aim at the shard
        that holds the entry, not redirect off the home filer."""
        return self._c(path)._u(path, **q)

    def _all(self) -> list[FilerClient]:
        return [self._clients[m] for m in self.ring.members()]

    # -- object level ---------------------------------------------------------
    def put_object(self, path: str, body: bytes, content_type: str = "",
                   extended: Optional[dict] = None,
                   signatures: Optional[list[int]] = None) -> dict:
        return self._c(path).put_object(
            path, body, content_type=content_type, extended=extended,
            signatures=signatures)

    def put_object_stream(self, path: str, rfile, length: int,
                          content_type: str = "",
                          extended: Optional[dict] = None) -> dict:
        return self._c(path).put_object_stream(
            path, rfile, length, content_type=content_type, extended=extended)

    def get_object(self, path: str, rng: Optional[str] = None):
        return self._c(path).get_object(path, rng=rng)

    def get_object_stream(self, path: str, rng: Optional[str] = None):
        return self._c(path).get_object_stream(path, rng=rng)

    def select(self, path: str, request_xml: bytes):
        return self._c(path).select(path, request_xml)

    # -- entry level ----------------------------------------------------------
    def get_entry(self, path: str) -> Optional[dict]:
        if self.ring.active and self.ring.is_spine(path):
            # spine dirs exist per-filer; first hit wins (they're replicas)
            for c in self._all():
                e = c.get_entry(path)
                if e is not None:
                    return e
            return None
        return self._c(path).get_entry(path)

    def create_entry(self, path: str, entry: dict,
                     signatures: Optional[list[int]] = None) -> None:
        self._c(path).create_entry(path, entry, signatures=signatures)

    def mkdir(self, path: str, signatures: Optional[list[int]] = None) -> None:
        if self.ring.active and self.ring.is_spine(path):
            for c in self._all():
                c.mkdir(path, signatures=signatures)
            return
        self._c(path).mkdir(path, signatures=signatures)

    def delete(self, path: str, recursive: bool = False,
               skip_chunk_purge: bool = False,
               signatures: Optional[list[int]] = None) -> int:
        if self.ring.active and self.ring.is_spine(path):
            worst = 0
            for c in self._all():
                s = c.delete(path, recursive=recursive,
                             skip_chunk_purge=skip_chunk_purge,
                             signatures=signatures)
                worst = max(worst, s if s != 404 else 0)
            # a spine dir absent on some members is still a success: 404s
            # only count when NOBODY had it
            return worst or 404
        return self._c(path).delete(
            path, recursive=recursive, skip_chunk_purge=skip_chunk_purge,
            signatures=signatures)

    def list(self, dir_path: str, start_after: str = "", limit: int = 1000,
             prefix: str = "") -> list[dict]:
        if not (self.ring.active and self.ring.is_spine(dir_path)):
            return self._c(dir_path).list(
                dir_path, start_after=start_after, limit=limit, prefix=prefix)
        # spine listing: fan out and merge by name. Children of a spine
        # dir may live anywhere (depth-boundary entries are sharded;
        # deeper spine dirs are replicated on every member) — dedupe by
        # name, keep the richest copy, present one sorted view.
        merged: dict[str, dict] = {}
        for c in self._all():
            for e in c.list(dir_path, start_after=start_after,
                            limit=limit, prefix=prefix):
                name = e.get("name", "")
                prev = merged.get(name)
                if prev is None or (
                        not prev.get("is_directory") and e.get("is_directory")):
                    merged[name] = e
        return [merged[k] for k in sorted(merged)][:limit]

    def rename(self, old: str, new: str) -> None:
        if not self.ring.active or self.ring.owner(old) == self.ring.owner(new):
            self._c(old).rename(old, new)
            return
        self._move_tree(old, new)

    def _move_tree(self, old: str, new: str) -> None:
        """Cross-shard rename: entry-level copy to the new owner, then a
        metadata-only delete at the old (chunks stay put — fids don't
        change, exactly the reshard discipline)."""
        src, dst = self._c(old), self._c(new)
        entry = src.get_entry(old)
        if entry is None:
            raise FilerHTTPError("MOVE", old, 404)
        self._copy_tree(src, dst, old, new, entry)
        src.delete(old, recursive=True, skip_chunk_purge=True)

    def _copy_tree(self, src: FilerClient, dst: FilerClient,
                   old: str, new: str, entry: dict) -> None:
        entry = dict(entry)
        entry["full_path"] = new
        dst.create_entry(new, entry)
        if entry.get("is_directory"):
            cursor = ""
            while True:
                page = src.list(old, start_after=cursor, limit=1000)
                if not page:
                    break
                for child in page:
                    cursor = child["name"]
                    ce = src.get_entry(f"{old.rstrip('/')}/{child['name']}")
                    if ce is not None:
                        self._copy_tree(
                            src, dst,
                            f"{old.rstrip('/')}/{child['name']}",
                            f"{new.rstrip('/')}/{child['name']}", ce)
                if len(page) < 1000:
                    break

    # -- passthrough (non-path-routed) ----------------------------------------
    def assign(self, count: int = 1, collection: str = "", ttl: str = "") -> dict:
        return self._home.assign(count=count, collection=collection, ttl=ttl)

    def status(self) -> dict:
        return self._home.status()

    def meta_events(self, since_ns: int = 0, limit: int = 1000) -> dict:
        return self._home.meta_events(since_ns=since_ns, limit=limit)

    def kv_put(self, key: str, value: bytes) -> None:
        self._home.kv_put(key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._home.kv_get(key)

    def kv_delete(self, key: str) -> None:
        self._home.kv_delete(key)


def make_client(filers: "str | list[str]", retry_reads: bool = True):
    """One factory for every gateway: a single address → plain
    FilerClient (zero behavior change); several → RingFilerClient.
    Accepts 'host:p1,host:p2' strings so CLI flags stay one value."""
    if isinstance(filers, str):
        filers = [f for f in filers.split(",") if f.strip()]
    if len(filers) <= 1:
        return FilerClient(filers[0], retry_reads=retry_reads)
    return RingFilerClient(filers, retry_reads=retry_reads)
