"""Thin client over the filer HTTP surface used by the S3 gateway.

Stands in for the reference's filer gRPC client (`s3api/filer_util.go`,
`filer_pb.SeaweedFiler`): entry-level lookup/create for multipart chunk-list
assembly, plus plain object read/write proxying.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Optional

from ..server.http_util import (
    http_bytes,
    http_bytes_headers,
    http_json,
    http_stream_request,
    http_stream_response,
)
from ..util.retry import READ_POLICY, RetryError, retry_call


class FilerHTTPError(IOError):
    """Non-2xx from the filer, with the status attached so retry
    classifiers can split transient (5xx/429) from poison (4xx) without
    parsing the message string."""

    def __init__(self, op: str, path: str, status: int, body: bytes = b""):
        super().__init__(f"{op} {path}: HTTP {status} {body[:200]!r}")
        self.status = status


class FilerClient:
    def __init__(self, filer_url: str, retry_reads: bool = True):
        self.base = f"http://{filer_url}"
        # idempotent reads ride the shared bounded-retry helper so a
        # connection reset mid-failover doesn't surface as a user error;
        # writes are NOT retried here — their callers (replication, s3
        # gateway) own retry policy and double-retrying multiplies load
        self._read_policy = READ_POLICY if retry_reads else None

    @staticmethod
    def _redirect_location(status: int, hdrs: dict) -> Optional[str]:
        """A sharded filer fleet answers reads for foreign paths with
        ``307 Location:`` (filer_server ring gate); a dumb client follows
        that ONE hop — the target answers with noRedirect, so there is
        never a chain. Writes aren't followed: the filer proxies those."""
        if status in (301, 302, 307, 308):
            return hdrs.get("Location") or hdrs.get("location")
        return None

    def _read(self, fn, *args, **kwargs):
        if self._read_policy is None:
            return fn(*args, **kwargs)
        try:
            return retry_call(fn, *args, policy=self._read_policy, **kwargs)
        except RetryError as e:
            raise e.last  # callers keep seeing the original URLError/OSError

    def _u(self, path: str, **q) -> str:
        qs = urllib.parse.urlencode({k: v for k, v in q.items() if v != ""})
        return self.base + urllib.parse.quote(path) + ("?" + qs if qs else "")

    # -- object level ---------------------------------------------------------
    # All four object calls ride the pooled keep-alive transport
    # (http_util): a gateway→filer hop per part/chunk no longer pays TCP
    # setup + slow-start; worker threads in the pipelined paths each keep
    # their own warm socket (the pool is thread-local).
    def put_object(
        self,
        path: str,
        body: bytes,
        content_type: str = "",
        extended: Optional[dict] = None,
        signatures: Optional[list[int]] = None,
    ) -> dict:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        for k, v in (extended or {}).items():
            headers[f"Seaweed-{k}"] = v
        status, data, _ = http_bytes_headers(
            "PUT",
            self._u(path, sig=",".join(map(str, signatures or []))),
            body=body,
            timeout=60,
            headers=headers,
        )
        if status >= 300:
            raise FilerHTTPError("PUT", path, status, data)
        return json.loads(data)

    def put_object_stream(
        self,
        path: str,
        rfile,
        length: int,
        content_type: str = "",
        extended: Optional[dict] = None,
    ) -> dict:
        """PUT with the body streamed from a file-like source: http.client's
        blocksize loop feeds the pooled socket, and the filer's streaming
        write path chunks it on arrival — an upload of any size flows
        end-to-end in bounded memory. The source is clamped to `length`
        bytes and a short read raises instead of silently truncating."""

        class _Exact:
            def __init__(self, src, left):
                self._src, self._left = src, left

            def read(self, n=-1):
                if self._left <= 0:
                    return b""
                want = self._left if n is None or n < 0 else min(n, self._left)
                got = self._src.read(want)
                if not got:
                    raise IOError(f"source ended {self._left} bytes early")
                self._left -= len(got)
                return got

        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        for k, v in (extended or {}).items():
            headers[f"Seaweed-{k}"] = v
        status, data, _ = http_stream_request(
            "PUT", self._u(path), _Exact(rfile, length), length,
            headers=headers, timeout=600,
        )
        if status >= 300:
            raise FilerHTTPError("PUT", path, status, data)
        return json.loads(data)

    def get_object_stream(
        self, path: str, rng: Optional[str] = None
    ) -> tuple[int, object, dict]:
        """GET whose body stays ON THE WIRE: returns (status, file-like
        response, headers) so gateways can pass bytes through piecewise
        instead of buffering whole objects (pairs with the filer's
        streaming read path). The caller must .close() the response; error
        statuses return the (small) error body as bytes instead."""
        headers = {"Range": rng} if rng else None
        status, body, hdrs = http_stream_response(
            "GET", self._u(path), headers=headers, timeout=600,
        )
        loc = self._redirect_location(status, hdrs)
        if loc:
            if hasattr(body, "read"):
                try:
                    body.read()  # tiny JSON; settle framing → repool
                finally:
                    body.close()
            status, body, hdrs = http_stream_response(
                "GET", loc, headers=headers, timeout=600,
            )
        return status, body, hdrs

    def get_object(
        self, path: str, rng: Optional[str] = None
    ) -> tuple[int, bytes, dict]:
        headers = {"Range": rng} if rng else None

        def go():
            status, data, hdrs = http_bytes_headers(
                "GET", self._u(path), headers=headers, timeout=60,
            )
            loc = self._redirect_location(status, hdrs)
            if loc:
                status, data, hdrs = http_bytes_headers(
                    "GET", loc, headers=headers, timeout=60,
                )
            return status, data, hdrs

        return self._read(go)

    def select(self, path: str, request_xml: bytes) -> tuple[int, bytes, dict]:
        """POST the raw SelectObjectContent request XML to the filer's
        /_select for ``path`` → (status, event_stream_bytes, error_dict).
        On success the body is the framed AWS event stream; on rejection
        the filer's JSON error (with its S3 ``error_code``) is decoded so
        the gateway can map it onto the wire."""
        status, data, _ = http_bytes_headers(
            "POST",
            self.base + "/_select?"
            + urllib.parse.urlencode({"path": path}),
            body=request_xml,
            timeout=600,
            headers={"Content-Type": "application/xml"},
        )
        if status == 200:
            return 200, data, {}
        try:
            err = json.loads(data)
        except ValueError:
            err = {"error": data.decode("utf-8", "replace")[:200]}
        return status, b"", err

    # -- entry level ----------------------------------------------------------
    def get_entry(self, path: str) -> Optional[dict]:
        def go():
            status, body, hdrs = http_bytes_headers(
                "GET", self._u(path, meta="true")
            )
            loc = self._redirect_location(status, hdrs)
            if loc:
                status, body, hdrs = http_bytes_headers("GET", loc)
            return status, body

        status, body = self._read(go)
        if status != 200:
            return None
        return json.loads(body)

    def create_entry(
        self, path: str, entry: dict, signatures: Optional[list[int]] = None
    ) -> None:
        http_json(
            "POST",
            self._u(
                path, meta="true", sig=",".join(map(str, signatures or []))
            ),
            body=entry,
        )

    def mkdir(self, path: str, signatures: Optional[list[int]] = None) -> None:
        http_json(
            "POST",
            self._u(
                path.rstrip("/") + "/", mkdir="true",
                sig=",".join(map(str, signatures or [])),
            ),
        )

    def delete(
        self,
        path: str,
        recursive: bool = False,
        skip_chunk_purge: bool = False,
        signatures: Optional[list[int]] = None,
    ) -> int:
        status, _ = http_bytes(
            "DELETE",
            self._u(
                path,
                recursive="true" if recursive else "",
                ignoreRecursiveError="true" if recursive else "",
                skipChunkPurge="true" if skip_chunk_purge else "",
                sig=",".join(map(str, signatures or [])),
            ),
        )
        return status

    def list(
        self,
        dir_path: str,
        start_after: str = "",
        limit: int = 1000,
        prefix: str = "",
    ) -> list[dict]:
        url = self._u(
            dir_path.rstrip("/") + "/",
            meta="true",
            lastFileName=start_after,
            limit=str(limit),
            prefix=prefix,
        )

        def go():
            status, body, hdrs = http_bytes_headers("GET", url)
            loc = self._redirect_location(status, hdrs)
            if loc:
                status, body, hdrs = http_bytes_headers("GET", loc)
            return status, body

        status, body = self._read(go)
        if status != 200:
            return []
        return json.loads(body).get("entries", [])

    def rename(self, old: str, new: str) -> None:
        http_json("POST", self._u(old, **{"mv.to": new}))

    def assign(self, count: int = 1, collection: str = "", ttl: str = "") -> dict:
        """AssignVolume through the filer (pb/filer.proto AssignVolume) —
        write-through clients (mount) get fids without master access."""
        return http_json(
            "GET",
            self.base
            + f"/_assign?count={count}&collection={collection}&ttl={ttl}",
        )

    # -- meta subscribe / kv / status ----------------------------------------
    def status(self) -> dict:
        return self._read(http_json, "GET", self.base + "/_status")

    def meta_events(self, since_ns: int = 0, limit: int = 1000) -> dict:
        return self._read(
            http_json,
            "GET",
            self.base + f"/_meta/events?since_ns={since_ns}&limit={limit}",
        )

    def kv_put(self, key: str, value: bytes) -> None:
        http_bytes("PUT", self.base + "/_kv/" + urllib.parse.quote(key), value)

    def kv_get(self, key: str) -> Optional[bytes]:
        status, body = self._read(
            http_bytes, "GET", self.base + "/_kv/" + urllib.parse.quote(key)
        )
        return body if status == 200 else None

    def kv_delete(self, key: str) -> None:
        http_bytes("DELETE", self.base + "/_kv/" + urllib.parse.quote(key))
