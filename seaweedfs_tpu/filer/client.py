"""Thin client over the filer HTTP surface used by the S3 gateway.

Stands in for the reference's filer gRPC client (`s3api/filer_util.go`,
`filer_pb.SeaweedFiler`): entry-level lookup/create for multipart chunk-list
assembly, plus plain object read/write proxying.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Optional

from ..server.http_util import http_bytes, http_json


class FilerClient:
    def __init__(self, filer_url: str):
        self.base = f"http://{filer_url}"

    def _u(self, path: str, **q) -> str:
        qs = urllib.parse.urlencode({k: v for k, v in q.items() if v != ""})
        return self.base + urllib.parse.quote(path) + ("?" + qs if qs else "")

    # -- object level ---------------------------------------------------------
    def put_object(
        self,
        path: str,
        body: bytes,
        content_type: str = "",
        extended: Optional[dict] = None,
        signatures: Optional[list[int]] = None,
    ) -> dict:
        req = urllib.request.Request(
            self._u(path, sig=",".join(map(str, signatures or []))),
            data=body,
            method="PUT",
        )
        if content_type:
            req.add_header("Content-Type", content_type)
        for k, v in (extended or {}).items():
            req.add_header(f"Seaweed-{k}", v)
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def put_object_stream(
        self,
        path: str,
        rfile,
        length: int,
        content_type: str = "",
        extended: Optional[dict] = None,
    ) -> dict:
        """PUT with the body streamed from a file-like source: urllib feeds
        http.client's blocksize loop, and the filer's streaming write path
        chunks it on arrival — an upload of any size flows end-to-end in
        bounded memory. The source is clamped to `length` bytes and a short
        read raises instead of silently truncating."""

        class _Exact:
            def __init__(self, src, left):
                self._src, self._left = src, left

            def read(self, n=-1):
                if self._left <= 0:
                    return b""
                want = self._left if n is None or n < 0 else min(n, self._left)
                got = self._src.read(want)
                if not got:
                    raise IOError(f"source ended {self._left} bytes early")
                self._left -= len(got)
                return got

        req = urllib.request.Request(
            self._u(path), data=_Exact(rfile, length), method="PUT"
        )
        req.add_header("Content-Length", str(length))
        if content_type:
            req.add_header("Content-Type", content_type)
        for k, v in (extended or {}).items():
            req.add_header(f"Seaweed-{k}", v)
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())

    def get_object_stream(
        self, path: str, rng: Optional[str] = None
    ) -> tuple[int, object, dict]:
        """GET whose body stays ON THE WIRE: returns (status, file-like
        response, headers) so gateways can pass bytes through piecewise
        instead of buffering whole objects (pairs with the filer's
        streaming read path). The caller must .close() the response; error
        statuses return the (small) error body as bytes instead."""
        req = urllib.request.Request(self._u(path), method="GET")
        if rng:
            req.add_header("Range", rng)
        try:
            resp = urllib.request.urlopen(req, timeout=600)
            return resp.status, resp, dict(resp.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            e.close()
            return e.code, body, dict(e.headers)

    def get_object(
        self, path: str, rng: Optional[str] = None
    ) -> tuple[int, bytes, dict]:
        req = urllib.request.Request(self._u(path), method="GET")
        if rng:
            req.add_header("Range", rng)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    # -- entry level ----------------------------------------------------------
    def get_entry(self, path: str) -> Optional[dict]:
        status, body = http_bytes("GET", self._u(path, meta="true"))
        if status != 200:
            return None
        return json.loads(body)

    def create_entry(
        self, path: str, entry: dict, signatures: Optional[list[int]] = None
    ) -> None:
        http_json(
            "POST",
            self._u(
                path, meta="true", sig=",".join(map(str, signatures or []))
            ),
            body=entry,
        )

    def mkdir(self, path: str) -> None:
        http_json("POST", self._u(path.rstrip("/") + "/", mkdir="true"))

    def delete(
        self,
        path: str,
        recursive: bool = False,
        skip_chunk_purge: bool = False,
        signatures: Optional[list[int]] = None,
    ) -> int:
        status, _ = http_bytes(
            "DELETE",
            self._u(
                path,
                recursive="true" if recursive else "",
                ignoreRecursiveError="true" if recursive else "",
                skipChunkPurge="true" if skip_chunk_purge else "",
                sig=",".join(map(str, signatures or [])),
            ),
        )
        return status

    def list(
        self,
        dir_path: str,
        start_after: str = "",
        limit: int = 1000,
        prefix: str = "",
    ) -> list[dict]:
        status, body = http_bytes(
            "GET",
            self._u(
                dir_path.rstrip("/") + "/",
                meta="true",
                lastFileName=start_after,
                limit=str(limit),
                prefix=prefix,
            ),
        )
        if status != 200:
            return []
        return json.loads(body).get("entries", [])

    def rename(self, old: str, new: str) -> None:
        http_json("POST", self._u(old, **{"mv.to": new}))

    def assign(self, count: int = 1, collection: str = "", ttl: str = "") -> dict:
        """AssignVolume through the filer (pb/filer.proto AssignVolume) —
        write-through clients (mount) get fids without master access."""
        return http_json(
            "GET",
            self.base
            + f"/_assign?count={count}&collection={collection}&ttl={ttl}",
        )

    # -- meta subscribe / kv / status ----------------------------------------
    def status(self) -> dict:
        return http_json("GET", self.base + "/_status")

    def meta_events(self, since_ns: int = 0, limit: int = 1000) -> dict:
        return http_json(
            "GET",
            self.base + f"/_meta/events?since_ns={since_ns}&limit={limit}",
        )

    def kv_put(self, key: str, value: bytes) -> None:
        http_bytes("PUT", self.base + "/_kv/" + urllib.parse.quote(key), value)

    def kv_get(self, key: str) -> Optional[bytes]:
        status, body = http_bytes(
            "GET", self.base + "/_kv/" + urllib.parse.quote(key)
        )
        return body if status == 200 else None

    def kv_delete(self, key: str) -> None:
        http_bytes("DELETE", self.base + "/_kv/" + urllib.parse.quote(key))
