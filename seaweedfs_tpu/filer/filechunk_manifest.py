"""Chunk manifests: chunk-of-chunks packing for huge files.

Reference: `weed/filer/filechunk_manifest.go` — when a file accumulates
more than ManifestBatch (1000) chunks, each full batch is serialized and
stored as one *manifest chunk* whose `is_chunk_manifest` flag is set and
whose (offset, size) cover the span of its children
(`mergeIntoManifest` :160-188). Reads resolve manifests recursively
(`ResolveChunkManifest` :41) so TB-scale files keep O(size/chunk/1000)
entry metadata. Serialization here is JSON (the entry codec of this
build) instead of the reference's protobuf.
"""

from __future__ import annotations

import json
from typing import Callable

from .entry import FileChunk

MANIFEST_BATCH = 1000

# save(data: bytes) -> FileChunk with file_id/mtime filled in
SaveFunc = Callable[[bytes], FileChunk]
# read(file_id: str, cipher_key: str) -> chunk bytes
ReadFunc = Callable[[str, str], bytes]


def has_chunk_manifest(chunks: list[FileChunk]) -> bool:
    return any(c.is_chunk_manifest for c in chunks)


def separate_manifest_chunks(
    chunks: list[FileChunk],
) -> tuple[list[FileChunk], list[FileChunk]]:
    manifest = [c for c in chunks if c.is_chunk_manifest]
    data = [c for c in chunks if not c.is_chunk_manifest]
    return manifest, data


def serialize_manifest(chunks: list[FileChunk]) -> bytes:
    return json.dumps({"chunks": [c.to_dict() for c in chunks]}).encode()


def parse_manifest(data: bytes) -> list[FileChunk]:
    return [FileChunk.from_dict(d) for d in json.loads(data)["chunks"]]


def maybe_manifestize(
    save: SaveFunc,
    chunks: list[FileChunk],
    batch: int = MANIFEST_BATCH,
) -> list[FileChunk]:
    """Pack every full batch of data chunks into a manifest chunk
    (doMaybeManifestize). Existing manifest chunks pass through; the
    incomplete tail batch stays as plain chunks."""
    out = [c for c in chunks if c.is_chunk_manifest]
    data_chunks = [c for c in chunks if not c.is_chunk_manifest]
    i = 0
    while i + batch <= len(data_chunks):
        group = data_chunks[i : i + batch]
        blob = serialize_manifest(group)
        manifest = save(blob)
        manifest.is_chunk_manifest = True
        manifest.offset = min(c.offset for c in group)
        manifest.size = max(c.offset + c.size for c in group) - manifest.offset
        out.append(manifest)
        i += batch
    out.extend(data_chunks[i:])
    return out


def resolve_chunk_manifest(
    read: ReadFunc, chunks: list[FileChunk]
) -> list[FileChunk]:
    """Expand manifest chunks (recursively) into their data chunks
    (ResolveChunkManifest)."""
    out: list[FileChunk] = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        children = parse_manifest(read(c.file_id, c.cipher_key))
        out.extend(resolve_chunk_manifest(read, children))
    return out
