"""FilerStore plugins: the uniform KV/SQL adapter interface.

Mirrors `weed/filer/filerstore.go:20`: insert/update/find/delete/
delete_folder_children/list + KV. Implementations:

- MemoryStore (here): dict-backed (tests, scratch)
- SqliteStore / AbstractSqlStore / GenericSqlStore (abstract_sql.py):
  the SQL family, embedded sqlite by default, any DB-API driver by name
  (`abstract_sql/abstract_sql_store.go`)
- RedisStore (redis_store.py): redis-protocol networked store
  (`redis2/universal_redis_store.go`)
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

from .entry import Entry


class NotFoundError(KeyError):
    pass


class FilerStore:
    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_entries(
        self, dir_path: str, start_after: str = "", limit: int = 1000
    ) -> Iterator[Entry]:
        raise NotImplementedError

    # KV (filerstore.go KvPut/KvGet — used for offsets/checkpoints)
    def kv_put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def kv_delete(self, key: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    if len(path) > 1:
        path = path.rstrip("/")
    return path


class MemoryStore(FilerStore):
    def __init__(self) -> None:
        self._entries: dict[str, Entry] = {}
        self._kv: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._entries[_norm(entry.full_path)] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        e = self._entries.get(_norm(path))
        if e is None:
            raise NotFoundError(path)
        return e

    def delete_entry(self, path: str) -> None:
        with self._lock:
            self._entries.pop(_norm(path), None)

    def delete_folder_children(self, path: str) -> None:
        prefix = _norm(path)
        prefix = prefix if prefix.endswith("/") else prefix + "/"
        with self._lock:
            for k in [k for k in self._entries if k.startswith(prefix)]:
                del self._entries[k]

    def list_entries(self, dir_path: str, start_after: str = "", limit: int = 1000):
        d = _norm(dir_path)
        d_prefix = d if d.endswith("/") else d + "/"
        names = []
        with self._lock:
            for k, e in self._entries.items():
                if k.startswith(d_prefix) and "/" not in k[len(d_prefix) :]:
                    names.append((k[len(d_prefix) :], e))
        names.sort()
        count = 0
        for name, e in names:
            if start_after and name <= start_after:
                continue
            yield e
            count += 1
            if count >= limit:
                return

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._kv[key] = value

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._kv.get(key)

    def kv_delete(self, key: bytes) -> None:
        self._kv.pop(key, None)


def __getattr__(name):
    # SqliteStore/AbstractSqlStore live in abstract_sql (which imports this
    # module for the base class); resolve lazily to avoid the cycle while
    # keeping `from .filerstore import SqliteStore` working everywhere
    if name in ("SqliteStore", "AbstractSqlStore", "GenericSqlStore"):
        from . import abstract_sql

        return getattr(abstract_sql, name)
    raise AttributeError(name)
