"""FilerStore plugins: the uniform KV/SQL adapter interface.

Mirrors `weed/filer/filerstore.go:20`: insert/update/find/delete/
delete_folder_children/list + KV. Two implementations:

- MemoryStore: dict-backed (tests, scratch)
- SqliteStore: stdlib sqlite3 standing in for the reference's leveldb
  default and abstract_sql stores (same dirhash+name keying scheme as
  `abstract_sql/abstract_sql_store.go`)
"""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Iterator, Optional

from .entry import Entry


class NotFoundError(KeyError):
    pass


class FilerStore:
    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_entries(
        self, dir_path: str, start_after: str = "", limit: int = 1000
    ) -> Iterator[Entry]:
        raise NotImplementedError

    # KV (filerstore.go KvPut/KvGet — used for offsets/checkpoints)
    def kv_put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    if len(path) > 1:
        path = path.rstrip("/")
    return path


class MemoryStore(FilerStore):
    def __init__(self) -> None:
        self._entries: dict[str, Entry] = {}
        self._kv: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._entries[_norm(entry.full_path)] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        e = self._entries.get(_norm(path))
        if e is None:
            raise NotFoundError(path)
        return e

    def delete_entry(self, path: str) -> None:
        with self._lock:
            self._entries.pop(_norm(path), None)

    def delete_folder_children(self, path: str) -> None:
        prefix = _norm(path)
        prefix = prefix if prefix.endswith("/") else prefix + "/"
        with self._lock:
            for k in [k for k in self._entries if k.startswith(prefix)]:
                del self._entries[k]

    def list_entries(self, dir_path: str, start_after: str = "", limit: int = 1000):
        d = _norm(dir_path)
        d_prefix = d if d.endswith("/") else d + "/"
        names = []
        with self._lock:
            for k, e in self._entries.items():
                if k.startswith(d_prefix) and "/" not in k[len(d_prefix) :]:
                    names.append((k[len(d_prefix) :], e))
        names.sort()
        count = 0
        for name, e in names:
            if start_after and name <= start_after:
                continue
            yield e
            count += 1
            if count >= limit:
                return

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._kv[key] = value

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._kv.get(key)


class SqliteStore(FilerStore):
    """Entries keyed (dir, name) like abstract_sql; JSON meta blob."""

    def __init__(self, db_path: str = ":memory:"):
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS filemeta ("
                " dir TEXT NOT NULL, name TEXT NOT NULL, meta TEXT NOT NULL,"
                " PRIMARY KEY (dir, name))"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)"
            )
            self._db.commit()

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        path = _norm(path)
        if path == "/":
            return "", "/"
        d, _, name = path.rpartition("/")
        return d or "/", name

    def insert_entry(self, entry: Entry) -> None:
        d, name = self._split(entry.full_path)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO filemeta (dir, name, meta) VALUES (?,?,?)",
                (d, name, json.dumps(entry.to_dict())),
            )
            self._db.commit()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        d, name = self._split(path)
        with self._lock:
            row = self._db.execute(
                "SELECT meta FROM filemeta WHERE dir=? AND name=?", (d, name)
            ).fetchone()
        if row is None:
            raise NotFoundError(path)
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        with self._lock:
            self._db.execute(
                "DELETE FROM filemeta WHERE dir=? AND name=?", (d, name)
            )
            self._db.commit()

    def delete_folder_children(self, path: str) -> None:
        p = _norm(path)
        with self._lock:
            self._db.execute("DELETE FROM filemeta WHERE dir=?", (p,))
            self._db.execute(
                "DELETE FROM filemeta WHERE dir LIKE ?", (p.rstrip("/") + "/%",)
            )
            self._db.commit()

    def list_entries(self, dir_path: str, start_after: str = "", limit: int = 1000):
        d = _norm(dir_path)
        with self._lock:
            rows = self._db.execute(
                "SELECT meta FROM filemeta WHERE dir=? AND name>? "
                "ORDER BY name LIMIT ?",
                (d, start_after, limit),
            ).fetchall()
        for (meta,) in rows:
            yield Entry.from_dict(json.loads(meta))

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?,?)", (key, value)
            )
            self._db.commit()

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._db.execute("SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return row[0] if row else None

    def close(self) -> None:
        with self._lock:
            self._db.close()
