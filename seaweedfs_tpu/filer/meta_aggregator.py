"""MetaAggregator: merged cluster-wide metadata event feed across filer peers.

Mirrors `weed/filer/meta_aggregator.go:31-49` + `meta_replay.go`: every filer
subscribes to each peer's *local* meta stream (HTTP long-poll on
`/_meta/events`, the SubscribeLocalMetadata analog) and republishes into one
aggregated feed that `/_meta/watch` serves to clients. Per-peer resume
offsets are checkpointed in the filer store's KV (meta_aggregator.go:172-208
MetaAggregator offset save/load), so restarts resume where they left off.

Store-sharing detection (meta_aggregator.go:43): each filer writes its
signature into its store's KV at startup; if a peer's signature is already
visible in *our* store, the peer shares it and its events must NOT be
re-applied (they're already in the store) — only fed to watchers. Peers with
independent stores get their events replayed into ours, which is what keeps
N filers over N stores convergent.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..util.retry import RetryPolicy, backoff_delays
from .entry import Entry
from .filerstore import FilerStore, NotFoundError
from .meta_log import EventNotification, MetaLog

PEER_SIG_PREFIX = b"filer.peer.sig."
OFFSET_PREFIX = b"meta_agg.offset."

# paces re-polls of an unreachable (or stalling) peer; the follow loop
# itself never gives up — a filer peer being down is a normal state
_FOLLOW_BACKOFF = RetryPolicy(attempts=6, base_s=0.2, cap_s=5.0,
                              deadline_s=1e9, jitter=False)


def apply_event_to_store(store: FilerStore, ev: EventNotification) -> None:
    """Replay one peer mutation into the local store (meta_replay.go:15)."""
    old, new = ev.old_entry, ev.new_entry
    if old and (not new or old.get("full_path") != new.get("full_path")):
        try:
            store.delete_entry(old["full_path"])
        except (NotFoundError, KeyError):
            pass
    if new:
        store.insert_entry(Entry.from_dict(new))  # stores upsert


class MetaAggregator:
    def __init__(
        self,
        filer,
        self_url: str,
        peers: list[str],
        poll_wait_s: float = 8.0,
        feed: Optional[MetaLog] = None,
    ):
        self.filer = filer
        self.self_url = self_url
        self.peers = [p for p in peers if p and p != self_url]
        self.poll_wait_s = poll_wait_s
        # the merged feed is in-memory: it is reconstructible from the peers'
        # persisted logs + our own, exactly like the reference's
        # MetaAggregator.MetaLogBuffer
        self.feed = feed or MetaLog()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MetaAggregator":
        # self events flow straight through
        self.filer.meta_log.subscribe("meta_aggregator", self._on_self_event)
        for peer in self.peers:
            t = threading.Thread(
                target=self._follow_peer, args=(peer,), daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.filer.meta_log.unsubscribe("meta_aggregator")
        for t in self._threads:  # daemon threads; don't block shutdown on a
            t.join(timeout=0.2)  # long-poll that's still in flight

    def _on_self_event(self, ev: EventNotification) -> None:
        # the feed RE-STAMPS with local receive time (the reference does the
        # same when republishing): watch cursors are ts-based, so carrying a
        # peer's older origin ts would make late-arriving peer events sort
        # behind a cursor already advanced by our own events — lost forever
        self.feed.append(
            ev.directory,
            ev.old_entry,
            ev.new_entry,
            delete_chunks=ev.delete_chunks,
            signatures=ev.signatures,
            is_from_other_cluster=ev.is_from_other_cluster,
        )

    # -- peer following ------------------------------------------------------
    def _peer_shares_store(self, peer_signature: int) -> bool:
        return (
            self.filer.store.kv_get(
                PEER_SIG_PREFIX + str(peer_signature).encode()
            )
            is not None
        )

    def _offset_key(self, peer: str) -> bytes:
        return OFFSET_PREFIX + peer.encode()

    _MAX_APPLY_RETRIES = 5

    def _follow_peer(self, peer: str) -> None:
        from ..server.http_util import http_json

        store = self.filer.store
        shares_store: Optional[bool] = None
        since = int(store.kv_get(self._offset_key(peer)) or 0)
        delays = None  # lazily-made backoff_delays generator; None = healthy
        apply_failures: dict[int, int] = {}  # peer seq -> consecutive failures
        while not self._stop.is_set():
            try:
                if shares_store is None:
                    status = http_json("GET", f"http://{peer}/_status")
                    shares_store = self._peer_shares_store(
                        int(status.get("signature", 0))
                    )
                r = http_json(
                    "GET",
                    f"http://{peer}/_meta/events?since_ns={since}"
                    f"&wait_s={self.poll_wait_s}&limit=500",
                    timeout=self.poll_wait_s + 10,
                )
                delays = None  # a successful poll resets the schedule
            except Exception:
                shares_store = None  # peer may have restarted with a new store
                if delays is None:
                    delays = backoff_delays(_FOLLOW_BACKOFF)
                if self._stop.wait(next(delays, _FOLLOW_BACKOFF.cap_s)):
                    return
                continue
            oldest = int(r.get("oldest_ts_ns", 0))
            if since and oldest > since:
                # gap: peer pruned history past our offset — resync from the
                # start of what it still has (upserts make replay idempotent)
                since = 0
            events = r.get("events", [])
            applied_any = False
            stalled = False
            for d in events:
                ev = EventNotification.from_dict(d)
                if shares_store is False:
                    try:
                        apply_event_to_store(store, ev)
                        apply_failures.pop(ev.seq, None)
                    except Exception:
                        # do NOT advance past an unapplied event — that is
                        # silent store divergence. Retry it on the next poll
                        # (transient store errors heal); a poison event is
                        # skipped after _MAX_APPLY_RETRIES so one bad record
                        # can't stall the whole peer stream.
                        n = apply_failures.get(ev.seq, 0) + 1
                        apply_failures[ev.seq] = n
                        if n <= self._MAX_APPLY_RETRIES:
                            stalled = True
                            break
                        apply_failures.pop(ev.seq, None)
                self.feed.append(
                    ev.directory,
                    ev.old_entry,
                    ev.new_entry,
                    delete_chunks=ev.delete_chunks,
                    signatures=ev.signatures,
                    is_from_other_cluster=ev.is_from_other_cluster,
                )
                since = max(since, ev.ts_ns)
                applied_any = True
            if applied_any:
                store.kv_put(self._offset_key(peer), str(since).encode())
            if stalled:
                if delays is None:
                    delays = backoff_delays(_FOLLOW_BACKOFF)
                if self._stop.wait(next(delays, _FOLLOW_BACKOFF.cap_s)):
                    return
