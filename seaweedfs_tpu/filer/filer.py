"""Filer core: path→Entry CRUD with parent-dir auto-creation + deletion GC.

Mirrors `weed/filer/filer.go:30-253` + `filer_delete_entry.go`: creates
missing parent directories on insert, recursive delete collects chunk fids
for the deletion queue, every mutation notifies the meta log.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, Optional

from ..util import faultpoints, glog
from .entry import Entry, FileChunk
from .filechunks import compact_file_chunks, minus_chunks
from .filerstore import FilerStore, MemoryStore, NotFoundError
from .meta_log import MetaLog
from ..util.locks import make_rlock

# purge(fids) — wired to operation.delete_files by the daemon
ChunkPurger = Callable[[list[str]], None]


class Filer:
    def __init__(
        self,
        store: Optional[FilerStore] = None,
        chunk_purger: Optional[ChunkPurger] = None,
        meta_log_dir: Optional[str] = None,
    ):
        self.store = store or MemoryStore()
        self.meta_log = MetaLog(persist_dir=meta_log_dir)
        self.chunk_purger = chunk_purger
        # expands manifest chunks into their children before purging so
        # chunk-of-chunks files don't leak data chunks on delete/overwrite
        # (filer_delete_entry.go ResolveChunkManifest); the server wires a
        # resolver that can actually read manifest blobs
        self.chunk_resolver: Optional[Callable[[list], list]] = None
        self._lock = make_rlock("Filer._lock")
        self._ensure_root()

    def _fids(self, chunks) -> list[str]:
        if self.chunk_resolver is not None:
            try:
                resolved = self.chunk_resolver(chunks)
                # manifest fids themselves are garbage too
                return [c.file_id for c in chunks] + [
                    c.file_id
                    for c in resolved
                    if c.file_id not in {x.file_id for x in chunks}
                ]
            except Exception:
                # fall back: purge at least the listed fids; the manifest
                # chunks themselves become unreferenced garbage, so say so
                glog.V(1).info("manifest resolve failed; purging %d listed"
                               " fids only", len(chunks))
        return [c.file_id for c in chunks]

    def _ensure_root(self) -> None:
        try:
            self.store.find_entry("/")
        except NotFoundError:
            self.store.insert_entry(
                Entry(full_path="/", is_directory=True, mode=0o755)
            )

    # -- CRUD (filer.go:131-253) ---------------------------------------------
    def create_entry(
        self,
        entry: Entry,
        o_excl: bool = False,
        signatures: Optional[list[int]] = None,
    ) -> Entry:
        with self._lock:
            # the per-filer serialization point: a delay armed here models
            # a loaded metadata store (bench --probe-meta scales past it by
            # sharding the tree over more filers)
            faultpoints.fire("filer.meta.create", path=entry.full_path)
            self._ensure_parents(entry.parent)
            old = None
            try:
                old = self.store.find_entry(entry.full_path)
            except NotFoundError:
                pass
            if old is not None:
                if o_excl:
                    raise FileExistsError(entry.full_path)
                if old.is_directory and not entry.is_directory:
                    raise IsADirectoryError(entry.full_path)
                if old.is_directory and entry.is_directory:
                    # re-mkdir is a no-op and emits NO meta event: a
                    # replicated mkdir would otherwise echo between
                    # active-active clusters forever — each apply raising
                    # a fresh event the other side re-applies
                    return old
            if old is not None and old.hard_link_id and not entry.hard_link_id:
                # writing through a linked path updates the shared inode so
                # every link sees the new content (filerstore_hardlink.go)
                inode = self._resolve_hardlink(old)
                counter = inode.hard_link_counter
                entry.hard_link_id = old.hard_link_id
                self._write_hardlink_content(old.hard_link_id, entry, counter)
                old = inode  # garbage math uses the inode's real chunks
                self.meta_log.append(
                    entry.parent, old.to_dict(), entry.to_dict(),
                    signatures=signatures,
                )
                if old.chunks and self.chunk_purger:
                    garbage = minus_chunks(old.chunks, entry.chunks)
                    if garbage:
                        self.chunk_purger(self._fids(garbage))
                return entry
            self.store.insert_entry(entry)
        self.meta_log.append(
            entry.parent,
            old.to_dict() if old else None,
            entry.to_dict(),
            signatures=signatures,
        )
        # chunks shadowed by the overwrite become garbage
        if old is not None and old.chunks and self.chunk_purger:
            garbage = minus_chunks(old.chunks, entry.chunks)
            if garbage:
                self.chunk_purger(self._fids(garbage))
        return entry

    def _ensure_parents(self, dir_path: str) -> None:
        if dir_path == "/":
            return
        try:
            e = self.store.find_entry(dir_path)
            if not e.is_directory:
                raise NotADirectoryError(dir_path)
            return
        except NotFoundError:
            pass
        parent = dir_path.rsplit("/", 1)[0] or "/"
        self._ensure_parents(parent)
        d = Entry(full_path=dir_path, is_directory=True, mode=0o775)
        self.store.insert_entry(d)
        self.meta_log.append(parent, None, d.to_dict())

    # -- hardlinks (filer/filerstore_hardlink.go) ----------------------------
    # Linked paths are stubs carrying a hard_link_id; the shared "inode"
    # (attrs + chunk list + link counter) lives in the store's KV under
    # hardlink/<id>, so a write through any path is seen by all of them.
    _HARDLINK_KV = b"hardlink/"

    def _hardlink_key(self, hid: str) -> bytes:
        return self._HARDLINK_KV + hid.encode()

    def _resolve_hardlink(self, entry: Entry) -> Entry:
        if not entry.hard_link_id:
            return entry
        import json as _json

        raw = self.store.kv_get(self._hardlink_key(entry.hard_link_id))
        if not raw:
            return entry  # dangling stub: serve as-is
        content = _json.loads(raw)
        resolved = Entry.from_dict(content | {"full_path": entry.full_path})
        resolved.hard_link_id = entry.hard_link_id
        resolved.hard_link_counter = content.get("hard_link_counter", 1)
        return resolved

    def _write_hardlink_content(self, hid: str, entry: Entry, counter: int) -> None:
        import json as _json

        content = entry.to_dict()
        content["hard_link_counter"] = counter
        self.store.kv_put(self._hardlink_key(hid), _json.dumps(content).encode())

    def link(self, target_path: str, link_path: str) -> Entry:
        """Create a hardlink at link_path referencing target_path's inode
        (filer_grpc_server link handling for mount's Link op)."""
        import secrets as _secrets

        with self._lock:
            raw = self.store.find_entry(target_path)
            if raw.is_directory:
                raise IsADirectoryError(target_path)
            if raw.hard_link_id:
                hid = raw.hard_link_id
                inode = self._resolve_hardlink(raw)
                counter = inode.hard_link_counter + 1
            else:
                hid = _secrets.token_hex(8)
                inode = raw
                counter = 2
                stub = Entry(full_path=target_path)
                stub.hard_link_id = hid
                stub.mode = raw.mode
                self.store.update_entry(stub)
            self._write_hardlink_content(hid, inode, counter)
            link_stub = Entry(full_path=link_path)
            link_stub.hard_link_id = hid
            link_stub.mode = inode.mode
            self._ensure_parents(link_stub.parent)
            self.store.insert_entry(link_stub)
        resolved = self._resolve_hardlink(link_stub)
        self.meta_log.append(link_stub.parent, None, resolved.to_dict())
        return resolved

    def find_entry(self, path: str) -> Entry:
        return self._resolve_hardlink(self.store.find_entry(path))

    def update_entry(self, entry: Entry) -> Entry:
        with self._lock:
            old = self.store.find_entry(entry.full_path)  # must exist
            self.store.update_entry(entry)
        self.meta_log.append(entry.parent, old.to_dict(), entry.to_dict())
        return entry

    def append_chunks(self, path: str, chunks: list[FileChunk]) -> Entry:
        """AppendToEntry semantics (filer_grpc_server.go)."""
        with self._lock:
            try:
                entry = self.store.find_entry(path)
            except NotFoundError:
                entry = Entry(full_path=path)
            offset = entry.file_size()
            for c in chunks:
                c.offset = offset
                offset += c.size
            entry.chunks.extend(chunks)
            entry.mtime = int(time.time())
            return self.create_entry(entry)

    def delete_entry(
        self,
        path: str,
        recursive: bool = False,
        ignore_recursive_error: bool = False,
        skip_chunk_purge: bool = False,
        signatures: Optional[list[int]] = None,
    ) -> list[str]:
        """Returns the chunk fids queued for purging
        (filer_delete_entry.go:15). Chunks are purged once, at the top level.
        `skip_chunk_purge` drops the metadata but keeps the chunks — used when
        chunk ownership moved to another entry (S3 multipart complete,
        filer_multipart.go)."""
        fids = self._delete_entry(
            path, recursive, ignore_recursive_error, signatures
        )
        if fids and self.chunk_purger and not skip_chunk_purge:
            self.chunk_purger(fids)
        return fids

    def _delete_entry(
        self,
        path: str,
        recursive: bool,
        ignore_recursive_error: bool,
        signatures: Optional[list[int]] = None,
    ) -> list[str]:
        with self._lock:
            entry = self.store.find_entry(path)
            fids = []
            if entry.hard_link_id:
                # unlink: drop the stub, decrement the inode's counter;
                # chunks are purged only when the last link goes away. The
                # counter read-modify-write and the inode content update
                # must be serialized with create_entry/link through other
                # link paths (two racing unlinks would otherwise both read
                # the same counter and leak the chunks forever).
                hid = entry.hard_link_id
                inode = self._resolve_hardlink(entry)
                counter = inode.hard_link_counter - 1
                self.store.delete_entry(path)
                if counter <= 0:
                    self.store.kv_put(self._hardlink_key(hid), b"")
                    fids = self._fids(inode.chunks)
                else:
                    self._write_hardlink_content(hid, inode, counter)
                self.meta_log.append(
                    entry.parent,
                    inode.to_dict() | {"full_path": path},
                    None,
                    delete_chunks=bool(fids),
                    signatures=signatures,
                )
                return fids
            if entry.is_directory:
                children = list(self.store.list_entries(path, limit=1_000_000))
                if children and not recursive:
                    raise OSError(f"directory {path} not empty")
                for child in children:
                    try:
                        fids.extend(
                            self._delete_entry(
                                child.full_path, True, ignore_recursive_error, signatures
                            )
                        )
                    except Exception:
                        if not ignore_recursive_error:
                            raise
            fids.extend(self._fids(entry.chunks))
            self.store.delete_entry(path)
        self.meta_log.append(
            entry.parent,
            entry.to_dict(),
            None,
            delete_chunks=bool(fids),
            signatures=signatures,
        )
        return fids

    def list_entries(
        self, dir_path: str, start_after: str = "", limit: int = 1000
    ) -> Iterator[Entry]:
        for e in self.store.list_entries(dir_path, start_after, limit):
            yield self._resolve_hardlink(e)

    # -- maintenance ---------------------------------------------------------
    def compact_chunks(self, path: str) -> int:
        """Drop fully-shadowed chunks from an entry; purge them. Returns the
        number of garbage chunks removed."""
        entry = self.store.find_entry(path)
        compacted, garbage = compact_file_chunks(entry.chunks)
        if garbage:
            entry.chunks = compacted
            self.store.update_entry(entry)
            if self.chunk_purger:
                self.chunk_purger(self._fids(garbage))
        return len(garbage)

    def rename(self, old_path: str, new_path: str) -> Entry:
        """AtomicRenameEntry for files and (recursively) directories."""
        with self._lock:
            entry = self.store.find_entry(old_path)
            if entry.is_directory:
                for child in list(self.store.list_entries(old_path, limit=1_000_000)):
                    self.rename(
                        child.full_path, new_path + "/" + child.name
                    )
            # an overwritten destination's chunks become garbage
            displaced: list[str] = []
            try:
                dest = self.store.find_entry(new_path)
                displaced = self._fids(minus_chunks(dest.chunks, entry.chunks))
            except NotFoundError:
                pass
            new_entry = Entry.from_dict(entry.to_dict())
            new_entry.full_path = new_path
            self._ensure_parents(new_entry.parent)
            self.store.insert_entry(new_entry)
            self.store.delete_entry(old_path)
        self.meta_log.append(entry.parent, entry.to_dict(), new_entry.to_dict())
        if displaced and self.chunk_purger:
            self.chunk_purger(displaced)
        return new_entry
