"""Filer: directory/metadata layer over the object store.

Mirrors `weed/filer/`: entries are paths with attributes and chunk lists of
object-store fids; stores are pluggable (sqlite replaces leveldb/SQL here);
every mutation feeds a meta log with subscribe/replay.
"""
