"""Entry model: FullPath + Attr + chunk list (weed/filer/entry.go:32).

Serialization is JSON (the reference uses protobuf — `entry_codec.go`); the
field names mirror filer_pb so the mapping is 1:1.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class FileChunk:
    """One stored chunk of a file (pb/filer.proto FileChunk)."""

    file_id: str  # "3,01637037d6"
    offset: int  # logical offset within the file
    size: int
    mtime: int = 0  # ns; decides overlap winners
    etag: str = ""
    cipher_key: str = ""  # base64 AES-256-GCM key (filer.proto cipher_key)
    is_chunk_manifest: bool = False  # chunk-of-chunks marker (filer.proto)

    def to_dict(self) -> dict:
        d = {
            "file_id": self.file_id,
            "offset": self.offset,
            "size": self.size,
            "mtime": self.mtime,
            "etag": self.etag,
        }
        if self.cipher_key:
            d["cipher_key"] = self.cipher_key
        if self.is_chunk_manifest:
            d["is_chunk_manifest"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(
            file_id=d["file_id"],
            offset=d.get("offset", 0),
            size=d.get("size", 0),
            mtime=d.get("mtime", 0),
            etag=d.get("etag", ""),
            cipher_key=d.get("cipher_key", ""),
            is_chunk_manifest=d.get("is_chunk_manifest", False),
        )


@dataclass
class Entry:
    full_path: str  # absolute, "/" separated
    is_directory: bool = False
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mtime: int = field(default_factory=lambda: int(time.time()))
    crtime: int = field(default_factory=lambda: int(time.time()))
    mime: str = ""
    ttl_sec: int = 0
    collection: str = ""
    replication: str = ""
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict[str, str] = field(default_factory=dict)
    hard_link_id: str = ""
    hard_link_counter: int = 0

    @property
    def name(self) -> str:
        return self.full_path.rstrip("/").rsplit("/", 1)[-1]

    @property
    def parent(self) -> str:
        p = self.full_path.rstrip("/").rsplit("/", 1)[0]
        return p or "/"

    def file_size(self) -> int:
        return max((c.offset + c.size for c in self.chunks), default=0)

    def to_dict(self) -> dict:
        return {
            "full_path": self.full_path,
            "is_directory": self.is_directory,
            "mode": self.mode,
            "uid": self.uid,
            "gid": self.gid,
            "mtime": self.mtime,
            "crtime": self.crtime,
            "mime": self.mime,
            "ttl_sec": self.ttl_sec,
            "collection": self.collection,
            "replication": self.replication,
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": self.extended,
            "hard_link_id": self.hard_link_id,
            "hard_link_counter": self.hard_link_counter,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Entry":
        e = cls(full_path=d["full_path"])
        e.is_directory = d.get("is_directory", False)
        e.mode = d.get("mode", 0o660)
        e.uid = d.get("uid", 0)
        e.gid = d.get("gid", 0)
        e.mtime = d.get("mtime", 0)
        e.crtime = d.get("crtime", 0)
        e.mime = d.get("mime", "")
        e.ttl_sec = d.get("ttl_sec", 0)
        e.collection = d.get("collection", "")
        e.replication = d.get("replication", "")
        e.chunks = [FileChunk.from_dict(c) for c in d.get("chunks", [])]
        e.extended = d.get("extended", {})
        e.hard_link_id = d.get("hard_link_id", "")
        e.hard_link_counter = d.get("hard_link_counter", 0)
        return e
