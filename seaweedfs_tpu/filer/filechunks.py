"""Chunk overlap math: chunk list → visible intervals → read views.

Mirrors `weed/filer/filechunks.go:55-225`: chunks are applied in mtime order;
a newer chunk shadows the overlapped ranges of older ones, splitting them
when partially covered. A read range maps to ChunkViews (fid + in-chunk
offset + size) over the visible intervals.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from .entry import FileChunk

MAX_INT64 = (1 << 63) - 1


@dataclass(frozen=True)
class VisibleInterval:
    start: int
    stop: int
    file_id: str
    mtime: int
    chunk_offset: int  # offset within the stored chunk where this slice begins
    chunk_size: int
    cipher_key: str = ""  # base64 AES-256 key for encrypted chunks


@dataclass(frozen=True)
class ChunkView:
    file_id: str
    offset: int  # offset within the stored chunk
    size: int
    logic_offset: int  # offset within the logical file
    chunk_size: int
    cipher_key: str = ""  # base64 AES-256 key for encrypted chunks

    @property
    def is_full_chunk(self) -> bool:
        return self.size == self.chunk_size


def merge_into_visibles(
    visibles: list[VisibleInterval], chunk: FileChunk
) -> list[VisibleInterval]:
    """Apply one (newer) chunk over the visible set (MergeIntoVisibles)."""
    new_v = VisibleInterval(
        chunk.offset,
        chunk.offset + chunk.size,
        chunk.file_id,
        chunk.mtime,
        0,
        chunk.size,
        chunk.cipher_key,
    )
    if not visibles or visibles[-1].stop <= chunk.offset:
        return visibles + [new_v]
    chunk_stop = chunk.offset + chunk.size
    out: list[VisibleInterval] = []
    for v in visibles:
        if v.start < chunk.offset < v.stop:
            out.append(
                VisibleInterval(
                    v.start,
                    chunk.offset,
                    v.file_id,
                    v.mtime,
                    v.chunk_offset,
                    v.chunk_size,
                    v.cipher_key,
                )
            )
        if v.start < chunk_stop < v.stop:
            out.append(
                VisibleInterval(
                    chunk_stop,
                    v.stop,
                    v.file_id,
                    v.mtime,
                    v.chunk_offset + (chunk_stop - v.start),
                    v.chunk_size,
                    v.cipher_key,
                )
            )
        if chunk_stop <= v.start or v.stop <= chunk.offset:
            out.append(v)
    out.append(new_v)
    out.sort(key=lambda v: v.start)
    return out


def non_overlapping_visible_intervals(
    chunks: list[FileChunk],
) -> list[VisibleInterval]:
    ordered = sorted(chunks, key=lambda c: (c.mtime, c.file_id))
    visibles: list[VisibleInterval] = []
    for chunk in ordered:
        visibles = merge_into_visibles(visibles, chunk)
    return visibles


def view_from_visibles(
    visibles: list[VisibleInterval], offset: int, size: int
) -> list[ChunkView]:
    stop = MAX_INT64 if size == MAX_INT64 else offset + size
    if stop < offset:
        stop = MAX_INT64
    views = []
    for v in visibles:
        start = max(offset, v.start)
        end = min(stop, v.stop)
        if start < end:
            views.append(
                ChunkView(
                    file_id=v.file_id,
                    offset=start - v.start + v.chunk_offset,
                    size=end - start,
                    logic_offset=start,
                    chunk_size=v.chunk_size,
                    cipher_key=v.cipher_key,
                )
            )
    return views


def view_from_chunks(
    chunks: list[FileChunk], offset: int, size: int
) -> list[ChunkView]:
    return view_from_visibles(non_overlapping_visible_intervals(chunks), offset, size)


def compact_file_chunks(
    chunks: list[FileChunk],
) -> tuple[list[FileChunk], list[FileChunk]]:
    """(still-referenced, garbage) split (CompactFileChunks)."""
    visible_fids = {v.file_id for v in non_overlapping_visible_intervals(chunks)}
    compacted = [c for c in chunks if c.file_id in visible_fids]
    garbage = [c for c in chunks if c.file_id not in visible_fids]
    return compacted, garbage


def minus_chunks(
    a: list[FileChunk], b: list[FileChunk]
) -> list[FileChunk]:
    """Chunks in a but not b, by fid (DoMinusChunks)."""
    b_fids = {c.file_id for c in b}
    return [c for c in a if c.file_id not in b_fids]


def etag_of_chunks(chunks: list[FileChunk]) -> str:
    """Multi-chunk etag (filer/filechunks.go ETagChunks): md5-of-etags + count."""
    if len(chunks) == 1:
        return chunks[0].etag
    h = hashlib.md5()
    for c in sorted(chunks, key=lambda c: c.offset):
        h.update(c.etag.encode())
    return f"{h.hexdigest()}-{len(chunks)}"


def total_size(chunks: list[FileChunk]) -> int:
    return max((c.offset + c.size for c in chunks), default=0)
