"""SDK-backed FilerStore adapters: cassandra / mongodb / etcd / elastic.

Mirrors the reference's thin driver wrappers
(`weed/filer/cassandra/cassandra_store.go:234`, `mongodb/mongodb_store.go:297`,
`etcd/etcd_store.go:252`, `elastic/v7/elastic_store.go:403`): each store maps
the FilerStore interface onto one client library's primitives. Like the
reference, these are only usable where the client SDK is installed — they
raise a loud ImportError otherwise (the same gating shape as
replication.notification.KafkaQueue). The portable stores (memory, sqlite,
generic DB-API SQL, redis RESP) live in filerstore.py / abstract_sql.py /
redis_store.py and carry the test coverage; these adapters reuse the exact
entry serialization those stores pin down.

Data model (shared): an entry is stored as its `Entry.to_dict()` JSON under
(directory, name) — the split the reference uses so directory listings are
one range scan.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

from .entry import Entry
from .filerstore import FilerStore, NotFoundError, _norm


def _split(path: str) -> tuple[str, str]:
    p = _norm(path)
    if p == "/":
        return "/", ""
    d, _, n = p.rpartition("/")
    return d or "/", n


def _ser(entry: Entry) -> bytes:
    return json.dumps(entry.to_dict()).encode()


def _deser(path: str, raw: bytes) -> Entry:
    return Entry.from_dict(json.loads(raw))


class CassandraStore(FilerStore):
    """CQL keyspace with the reference's `filemeta` table
    (cassandra_store.go:36-57): PRIMARY KEY (directory, name)."""

    def __init__(self, hosts: list[str], keyspace: str = "seaweedfs",
                 username: str = "", password: str = "", port: int = 9042):
        try:
            from cassandra.cluster import Cluster  # type: ignore
            from cassandra.auth import PlainTextAuthProvider  # type: ignore
        except ImportError as e:
            raise ImportError(
                "CassandraStore needs the 'cassandra-driver' package; use "
                "the sqlite/sql/redis stores where it is unavailable"
            ) from e
        auth = (
            PlainTextAuthProvider(username=username, password=password)
            if username else None
        )
        self._cluster = Cluster(hosts, port=port, auth_provider=auth)
        self._s = self._cluster.connect(keyspace)
        self._s.execute(
            "CREATE TABLE IF NOT EXISTS filemeta (directory varchar, "
            "name varchar, meta blob, PRIMARY KEY (directory, name))"
        )
        self._s.execute(
            "CREATE TABLE IF NOT EXISTS key_value (key blob PRIMARY KEY, "
            "value blob)"
        )

    def insert_entry(self, entry: Entry) -> None:
        d, n = _split(entry.full_path)
        self._s.execute(
            "INSERT INTO filemeta (directory, name, meta) VALUES (%s,%s,%s)",
            (d, n, _ser(entry)),
        )

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        d, n = _split(path)
        rows = self._s.execute(
            "SELECT meta FROM filemeta WHERE directory=%s AND name=%s", (d, n)
        )
        row = rows.one()
        if row is None:
            raise NotFoundError(path)
        return _deser(path, bytes(row.meta))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        self._s.execute(
            "DELETE FROM filemeta WHERE directory=%s AND name=%s", (d, n)
        )

    def delete_folder_children(self, path: str) -> None:
        # direct children only: the partition key admits equality, not
        # ranges — exactly the reference's behavior (cassandra_store.go
        # DeleteFolderChildren). Subtree recursion happens in the filer
        # (filer.py _delete_entry walks directories), so nothing is lost.
        self._s.execute(
            "DELETE FROM filemeta WHERE directory=%s", (_norm(path),)
        )

    def list_entries(self, dir_path: str, start_after: str = "",
                     limit: int = 1000) -> Iterator[Entry]:
        rows = self._s.execute(
            "SELECT name, meta FROM filemeta WHERE directory=%s AND "
            "name>%s LIMIT %s",
            (_norm(dir_path), start_after, limit),
        )
        for row in rows:
            yield _deser(f"{dir_path}/{row.name}", bytes(row.meta))

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._s.execute(
            "INSERT INTO key_value (key, value) VALUES (%s,%s)", (key, value)
        )

    def kv_get(self, key: bytes) -> Optional[bytes]:
        row = self._s.execute(
            "SELECT value FROM key_value WHERE key=%s", (key,)
        ).one()
        return bytes(row.value) if row else None

    def kv_delete(self, key: bytes) -> None:
        self._s.execute("DELETE FROM key_value WHERE key=%s", (key,))

    def close(self) -> None:
        self._cluster.shutdown()


class MongoStore(FilerStore):
    """`filemeta` collection keyed on (directory, name)
    (mongodb_store.go:45-66)."""

    def __init__(self, uri: str = "mongodb://127.0.0.1:27017",
                 database: str = "seaweedfs"):
        try:
            import pymongo  # type: ignore
        except ImportError as e:
            raise ImportError(
                "MongoStore needs the 'pymongo' package; use the sqlite/"
                "sql/redis stores where it is unavailable"
            ) from e
        self._client = pymongo.MongoClient(uri)
        db = self._client[database]
        self._c = db["filemeta"]
        self._kv = db["key_value"]
        self._c.create_index([("directory", 1), ("name", 1)], unique=True)

    def insert_entry(self, entry: Entry) -> None:
        d, n = _split(entry.full_path)
        self._c.replace_one(
            {"directory": d, "name": n},
            {"directory": d, "name": n, "meta": _ser(entry)},
            upsert=True,
        )

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        d, n = _split(path)
        doc = self._c.find_one({"directory": d, "name": n})
        if doc is None:
            raise NotFoundError(path)
        return _deser(path, bytes(doc["meta"]))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        self._c.delete_one({"directory": d, "name": n})

    def delete_folder_children(self, path: str) -> None:
        import re

        # whole subtree, matching the portable stores' contract; root's
        # nested matcher must be "/" not "//" (abstract_sql rstrip parity)
        p = _norm(path)
        nested = (p.rstrip("/") + "/")
        self._c.delete_many({"$or": [
            {"directory": p},
            {"directory": {"$regex": "^" + re.escape(nested)}},
        ]})

    def list_entries(self, dir_path: str, start_after: str = "",
                     limit: int = 1000) -> Iterator[Entry]:
        cur = (
            self._c.find({"directory": _norm(dir_path),
                          "name": {"$gt": start_after}})
            .sort("name", 1)
            .limit(limit)
        )
        for doc in cur:
            yield _deser(f"{dir_path}/{doc['name']}", bytes(doc["meta"]))

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._kv.replace_one({"_id": key}, {"_id": key, "value": value},
                             upsert=True)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        doc = self._kv.find_one({"_id": key})
        return bytes(doc["value"]) if doc else None

    def kv_delete(self, key: bytes) -> None:
        self._kv.delete_one({"_id": key})

    def close(self) -> None:
        self._client.close()


class EtcdStore(FilerStore):
    """Entries under a key prefix, one key per path; listings are prefix
    range reads (etcd_store.go:24-43 DIR_FILE_SEPARATOR layout)."""

    def __init__(self, endpoint: str = "127.0.0.1:2379",
                 prefix: str = "seaweedfs."):
        try:
            import etcd3  # type: ignore
        except ImportError as e:
            raise ImportError(
                "EtcdStore needs the 'etcd3' package; use the sqlite/sql/"
                "redis stores where it is unavailable"
            ) from e
        host, _, port = endpoint.partition(":")
        self._c = etcd3.client(host=host, port=int(port or 2379))
        self._p = prefix

    def _key(self, path: str) -> str:
        d, n = _split(path)
        return f"{self._p}{d}\x00{n}"

    def insert_entry(self, entry: Entry) -> None:
        self._c.put(self._key(entry.full_path), _ser(entry))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        raw, _ = self._c.get(self._key(path))
        if raw is None:
            raise NotFoundError(path)
        return _deser(path, raw)

    def delete_entry(self, path: str) -> None:
        self._c.delete(self._key(path))

    def delete_folder_children(self, path: str) -> None:
        # two prefixes cover the subtree without clipping siblings:
        # "<dir>\x00" = direct children, "<dir rstripped>/" = all nested
        # directories ("/a" must not match "/ab\x00..."; root's nested
        # prefix is "/", not "//")
        p = _norm(path)
        self._c.delete_prefix(f"{self._p}{p}\x00")
        self._c.delete_prefix(f"{self._p}{p.rstrip('/')}/")

    def list_entries(self, dir_path: str, start_after: str = "",
                     limit: int = 1000) -> Iterator[Entry]:
        count = 0
        prefix = f"{self._p}{_norm(dir_path)}\x00"
        # server-side range from just past the cursor; `limit` is pushed to
        # etcd where the client supports it (RangeRequest.limit), so a page
        # transfers only its own entries — older python-etcd3 falls back to
        # fetching the range tail and breaking locally
        kwargs = {"sort_order": "ascend", "sort_target": "key"}
        if start_after:
            import etcd3.utils as _u  # type: ignore

            args = (prefix + start_after + "\x00",
                    _u.prefix_range_end(_u.to_bytes(prefix)))
            fetch = self._c.get_range
        else:
            args = (prefix,)
            fetch = self._c.get_prefix
        try:
            it = fetch(*args, limit=limit, **kwargs)
        except TypeError:
            it = fetch(*args, **kwargs)
        for raw, meta in it:
            if count >= limit:
                break  # keys arrive ascending: nothing more to take
            name = meta.key.decode()[len(prefix):]
            count += 1
            yield _deser(f"{dir_path}/{name}", raw)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._c.put(self._p + "kv." + key.hex(), value)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        raw, _ = self._c.get(self._p + "kv." + key.hex())
        return raw

    def kv_delete(self, key: bytes) -> None:
        self._c.delete(self._p + "kv." + key.hex())

    def close(self) -> None:
        self._c.close()


class ElasticStore(FilerStore):
    """Documents in one index, id = urlsafe path (elastic v7
    elastic_store.go:55-88)."""

    def __init__(self, servers: list[str], index: str = "seaweedfs"):
        try:
            from elasticsearch import Elasticsearch  # type: ignore
        except ImportError as e:
            raise ImportError(
                "ElasticStore needs the 'elasticsearch' package; use the "
                "sqlite/sql/redis stores where it is unavailable"
            ) from e
        import base64

        import elasticsearch as _es  # type: ignore

        self._b64 = base64.urlsafe_b64encode
        self._c = Elasticsearch(servers)
        self._index = index
        self._not_found = _es.NotFoundError

    def _id(self, path: str) -> str:
        return self._b64(_norm(path).encode()).decode()

    def insert_entry(self, entry: Entry) -> None:
        d, n = _split(entry.full_path)
        # refresh=wait_for: the filer's metadata reads are
        # read-your-writes everywhere else (a directory listing issued
        # right after a create MUST see the entry — _delete_entry counts
        # children through list_entries); default async refresh would
        # make just-written entries invisible for up to a second
        self._c.index(
            index=self._index, id=self._id(entry.full_path),
            body={"directory": d, "name": n,
                  "meta": _ser(entry).decode()},
            refresh="wait_for",
        )

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        try:
            doc = self._c.get(index=self._index, id=self._id(path))
        except self._not_found as e:
            # ONLY the index miss maps to NotFound; transport/connection
            # errors must propagate (an outage is not "file absent")
            raise NotFoundError(path) from e
        return _deser(path, doc["_source"]["meta"].encode())

    def delete_entry(self, path: str) -> None:
        try:
            self._c.delete(index=self._index, id=self._id(path),
                           refresh="wait_for")
        except self._not_found:
            pass

    def delete_folder_children(self, path: str) -> None:
        p = _norm(path)
        self._c.delete_by_query(
            index=self._index, refresh=True,
            body={"query": {"bool": {"should": [
                {"term": {"directory.keyword": p}},
                {"prefix": {"directory.keyword": p.rstrip("/") + "/"}},
            ], "minimum_should_match": 1}}},
        )

    def list_entries(self, dir_path: str, start_after: str = "",
                     limit: int = 1000) -> Iterator[Entry]:
        res = self._c.search(
            index=self._index,
            body={
                "size": limit,
                "sort": [{"name.keyword": "asc"}],
                "query": {
                    "bool": {
                        "must": [{"term": {"directory.keyword": _norm(dir_path)}}],
                        "filter": [{"range": {"name.keyword": {"gt": start_after}}}],
                    }
                },
            },
        )
        for hit in res["hits"]["hits"]:
            src = hit["_source"]
            yield _deser(f"{dir_path}/{src['name']}", src["meta"].encode())

    def kv_put(self, key: bytes, value: bytes) -> None:
        # no refresh: kv_get fetches by document id, which is realtime in
        # ES — waiting for an index refresh would only add write latency
        self._c.index(index=self._index + "_kv", id=key.hex(),
                      body={"value": value.hex()})

    def kv_get(self, key: bytes) -> Optional[bytes]:
        try:
            doc = self._c.get(index=self._index + "_kv", id=key.hex())
        except self._not_found:  # outages propagate; only misses are None
            return None
        return bytes.fromhex(doc["_source"]["value"])

    def kv_delete(self, key: bytes) -> None:
        try:
            self._c.delete(index=self._index + "_kv", id=key.hex())
        except self._not_found:
            pass

    def close(self) -> None:
        self._c.close()
