"""Meta log: every filer mutation as a persisted, subscribable event stream.

Mirrors `weed/filer/filer_notify.go` + `util/log_buffer/log_buffer.go`:
mutations append EventNotifications to an in-memory ring AND (when a persist
dir is configured) to on-disk jsonl segment files, the analog of the
reference flushing log-buffer segments as chunked files under
`/topics/.system/log/<date>/` (filer_notify.go:84 logFlushFunc). Subscribers
replay persisted-then-memory from a timestamp and tail live; restart loses
nothing.

Every event carries a monotonically increasing ``seq`` (persisted), so
subscribers can detect gaps: if a subscriber asks for events older than
``oldest_ts_ns()`` (e.g. after segments were pruned), the reply is flagged
and the client must resync from a snapshot — the fix for round-1's
silently-lossy ring.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..util import glog
from ..util.locks import make_condition, make_lock

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".jsonl"


@dataclass
class EventNotification:
    ts_ns: int
    directory: str
    old_entry: Optional[dict]  # Entry dicts (None for create/delete sides)
    new_entry: Optional[dict]
    delete_chunks: bool = False
    is_from_other_cluster: bool = False
    signatures: list[int] = field(default_factory=list)
    seq: int = 0

    def to_dict(self) -> dict:
        return {
            "ts_ns": self.ts_ns,
            "directory": self.directory,
            "old_entry": self.old_entry,
            "new_entry": self.new_entry,
            "delete_chunks": self.delete_chunks,
            "is_from_other_cluster": self.is_from_other_cluster,
            "signatures": self.signatures,
            "seq": self.seq,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EventNotification":
        return cls(
            ts_ns=d["ts_ns"],
            directory=d.get("directory", ""),
            old_entry=d.get("old_entry"),
            new_entry=d.get("new_entry"),
            delete_chunks=d.get("delete_chunks", False),
            is_from_other_cluster=d.get("is_from_other_cluster", False),
            signatures=d.get("signatures", []),
            seq=d.get("seq", 0),
        )


class MetaLog:
    def __init__(
        self,
        capacity: int = 100_000,
        persist_dir: Optional[str] = None,
        segment_events: int = 4096,
    ):
        self.capacity = capacity
        self.persist_dir = persist_dir
        self.segment_events = segment_events
        self._events: list[EventNotification] = []
        self._lock = make_lock("MetaLog._lock")
        self._cond = make_condition(self._lock)
        self._subscribers: dict[str, Callable[[EventNotification], None]] = {}
        self._next_seq = 1
        self._last_ts_ns = 0
        self._seg_fh = None
        self._seg_count = 0
        # (seq, ts) of the oldest surviving persisted event; a first seq > 1
        # means earlier history was pruned — detectable across restarts
        self._oldest_persisted: Optional[tuple[int, int]] = None
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._recover()

    # -- persistence ---------------------------------------------------------
    def _segments(self) -> list[str]:
        if not self.persist_dir:
            return []
        return sorted(
            f
            for f in os.listdir(self.persist_dir)
            if f.startswith(_SEG_PREFIX) and f.endswith(_SEG_SUFFIX)
        )

    def _recover(self) -> None:
        """Resume seq numbering (and oldest-available ts) from disk."""
        segs = self._segments()
        if not segs:
            return
        self._load_oldest(segs)
        # last seq: last line of the last segment
        last_seq = last_ts = 0
        with open(os.path.join(self.persist_dir, segs[-1])) as f:
            for line in f:
                line = line.strip()
                if line:
                    d = json.loads(line)
                    last_seq, last_ts = d["seq"], d["ts_ns"]
        self._next_seq = last_seq + 1
        # keep ts monotone across restarts too (clock may have stepped back)
        self._last_ts_ns = last_ts

    def _persist(self, ev: EventNotification) -> None:
        if not self.persist_dir:
            return
        if self._seg_fh is None or self._seg_count >= self.segment_events:
            if self._seg_fh is not None:
                self._seg_fh.close()
            name = f"{_SEG_PREFIX}{ev.seq:020d}{_SEG_SUFFIX}"
            self._seg_fh = open(os.path.join(self.persist_dir, name), "a")
            self._seg_count = 0
        self._seg_fh.write(json.dumps(ev.to_dict()) + "\n")
        self._seg_fh.flush()
        self._seg_count += 1
        if self._oldest_persisted is None:
            self._oldest_persisted = (ev.seq, ev.ts_ns)

    def _read_persisted(self, since_ts_ns: int) -> list[EventNotification]:
        out: list[EventNotification] = []
        for seg in self._segments():
            path = os.path.join(self.persist_dir, seg)
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        d = json.loads(line)
                        if d["ts_ns"] > since_ts_ns:
                            out.append(EventNotification.from_dict(d))
            except FileNotFoundError:
                continue  # pruned under us
        return out

    def prune_segments(self, keep: int = 8) -> int:
        """Drop all but the newest ``keep`` segments (log retention). Returns
        the number removed; subscribers older than the new oldest_ts get a
        gap signal on their next poll."""
        removed = 0
        with self._lock:
            segs = self._segments()
            for seg in segs[:-keep] if keep else segs:
                try:
                    os.remove(os.path.join(self.persist_dir, seg))
                    removed += 1
                except FileNotFoundError:
                    pass
            self._load_oldest(self._segments())
        return removed

    def _load_oldest(self, segs: list[str]) -> None:
        self._oldest_persisted = None
        if segs:
            with open(os.path.join(self.persist_dir, segs[0])) as f:
                first = f.readline().strip()
                if first:
                    d = json.loads(first)
                    self._oldest_persisted = (d["seq"], d["ts_ns"])

    # -- append / replay -----------------------------------------------------
    def append(
        self,
        directory: str,
        old_entry: Optional[dict],
        new_entry: Optional[dict],
        delete_chunks: bool = False,
        signatures: Optional[list[int]] = None,
        is_from_other_cluster: bool = False,
    ) -> EventNotification:
        ev = EventNotification(
            ts_ns=0,
            directory=directory,
            old_entry=old_entry,
            new_entry=new_entry,
            delete_chunks=delete_chunks,
            is_from_other_cluster=is_from_other_cluster,
            signatures=signatures or [],
        )
        with self._lock:
            # stamp under the lock so ts order always matches seq order —
            # a pre-lock stamp lets a preempted thread append an OLDER ts
            # after a newer one, and ts-cursor pollers then skip it forever
            ev.ts_ns = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ev.ts_ns
            ev.seq = self._next_seq
            self._next_seq += 1
            self._events.append(ev)
            if len(self._events) > self.capacity:
                self._events = self._events[-self.capacity :]
            self._persist(ev)
            subs = list(self._subscribers.values())
            self._cond.notify_all()
        for fn in subs:
            try:
                fn(ev)
            except Exception:
                glog.exception("meta-log subscriber failed")
        return ev

    def oldest_ts_ns(self) -> int:
        """Timestamp before which history is no longer available (0 = full
        history retained — poll with since_ns < this means events were lost
        to pruning and the subscriber must resync)."""
        with self._lock:
            if self.persist_dir:
                if self._oldest_persisted and self._oldest_persisted[0] > 1:
                    return self._oldest_persisted[1]
                return 0
            if self._events and self._events[0].seq > 1:  # ring dropped some
                return self._events[0].ts_ns
            return 0

    def replay_since(self, ts_ns: int) -> list[EventNotification]:
        """Persisted-then-memory replay, deduped by seq, ordered by seq."""
        with self._lock:
            mem = [e for e in self._events if e.ts_ns > ts_ns]
            mem_seqs = {e.seq for e in mem}
            # memory fast path: ts is monotone with seq, so when the ring's
            # oldest event is at or before the cursor (or the ring still holds
            # seq 1), everything after the cursor is in memory — skip the
            # full-segment disk scan that would otherwise run on every poll
            ring_covers = bool(self._events) and (
                self._events[0].seq == 1 or self._events[0].ts_ns <= ts_ns
            )
        if self.persist_dir and not ring_covers:
            disk = [
                e for e in self._read_persisted(ts_ns) if e.seq not in mem_seqs
            ]
            return sorted(disk + mem, key=lambda e: e.seq)
        return mem

    def wait_since(
        self, ts_ns: int, timeout: float = 0.0
    ) -> list[EventNotification]:
        """replay_since with long-poll: if empty, block up to ``timeout``
        seconds for a new event."""
        events = self.replay_since(ts_ns)
        if events or timeout <= 0:
            return events
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._events or self._events[-1].ts_ns <= ts_ns:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        return self.replay_since(ts_ns)

    # -- push subscribers ----------------------------------------------------
    def subscribe(
        self,
        name: str,
        fn: Callable[[EventNotification], None],
        since_ts_ns: int = 0,
    ) -> None:
        """Replay events after since_ts_ns, then tail live. The snapshot and
        registration happen under one lock hold so no event can fall between
        replay and tail (live events may interleave with the replay delivery,
        but none are lost)."""
        with self._lock:
            mem = [e for e in self._events if e.ts_ns > since_ts_ns]
            mem_seqs = {e.seq for e in mem}
            self._subscribers[name] = fn
        if self.persist_dir:
            disk = [
                e
                for e in self._read_persisted(since_ts_ns)
                if e.seq not in mem_seqs
            ]
            snapshot = sorted(disk + mem, key=lambda e: e.seq)
        else:
            snapshot = mem
        for ev in snapshot:
            fn(ev)

    def unsubscribe(self, name: str) -> None:
        with self._lock:
            self._subscribers.pop(name, None)

    def close(self) -> None:
        with self._lock:
            if self._seg_fh is not None:
                self._seg_fh.close()
                self._seg_fh = None
