"""Meta log: every filer mutation as a subscribable event stream.

Mirrors `weed/filer/filer_notify.go` + `util/log_buffer`: mutations append
EventNotifications to an in-memory ring; subscribers replay from a timestamp
then tail. (The reference also persists flushed segments as chunked files
under /topics/.system/log — persistence hook kept, in-memory by default.)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class EventNotification:
    ts_ns: int
    directory: str
    old_entry: Optional[dict]  # Entry dicts (None for create/delete sides)
    new_entry: Optional[dict]
    delete_chunks: bool = False
    is_from_other_cluster: bool = False
    signatures: list[int] = field(default_factory=list)


class MetaLog:
    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._events: list[EventNotification] = []
        self._lock = threading.Lock()
        self._subscribers: dict[str, Callable[[EventNotification], None]] = {}

    def append(
        self,
        directory: str,
        old_entry: Optional[dict],
        new_entry: Optional[dict],
        delete_chunks: bool = False,
        signatures: Optional[list[int]] = None,
    ) -> EventNotification:
        ev = EventNotification(
            ts_ns=time.time_ns(),
            directory=directory,
            old_entry=old_entry,
            new_entry=new_entry,
            delete_chunks=delete_chunks,
            signatures=signatures or [],
        )
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self.capacity:
                self._events = self._events[-self.capacity :]
            subs = list(self._subscribers.values())
        for fn in subs:
            try:
                fn(ev)
            except Exception:
                pass
        return ev

    def replay_since(self, ts_ns: int) -> list[EventNotification]:
        with self._lock:
            return [e for e in self._events if e.ts_ns > ts_ns]

    def subscribe(
        self,
        name: str,
        fn: Callable[[EventNotification], None],
        since_ts_ns: int = 0,
    ) -> None:
        """Replay events after since_ts_ns, then tail live. The snapshot and
        registration happen under one lock hold so no event can fall between
        replay and tail (live events may interleave with the replay delivery,
        but none are lost)."""
        with self._lock:
            snapshot = [e for e in self._events if e.ts_ns > since_ts_ns]
            self._subscribers[name] = fn
        for ev in snapshot:
            fn(ev)

    def unsubscribe(self, name: str) -> None:
        with self._lock:
            self._subscribers.pop(name, None)
