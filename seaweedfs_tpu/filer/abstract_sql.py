"""Generic SQL FilerStore over any DB-API 2.0 connection.

Mirrors `weed/filer/abstract_sql/abstract_sql_store.go`: one `filemeta`
table keyed (dir, name) with a serialized meta blob, plus a `kv` table for
checkpoints. The concrete dialect supplies a connection factory and its
paramstyle; `SqliteStore` (filerstore.py) is the embedded instance, and
any networked DB-API driver (mysql/postgres-style `format` placeholders or
`qmark`) plugs in through `GenericSqlStore` without subclassing.
"""

from __future__ import annotations

import json
import threading
from typing import Iterator, Optional

from .entry import Entry
from .filerstore import FilerStore, NotFoundError, _norm

_PLACEHOLDER = {"qmark": "?", "format": "%s", "pyformat": "%s"}

# dialect → (filemeta DDL, kv DDL, upsert template). The schema follows
# abstract_sql_store.go: mysql needs sized key columns (no TEXT in a PK),
# postgres spells blobs BYTEA and upserts via ON CONFLICT.
_DIALECTS = {
    "sqlite": (
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dir TEXT NOT NULL, name TEXT NOT NULL, meta TEXT NOT NULL,"
        " PRIMARY KEY (dir, name))",
        "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)",
        "INSERT OR REPLACE INTO {table} ({cols}) VALUES ({ph})",
    ),
    "mysql": (
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dir VARCHAR(766) NOT NULL, name VARCHAR(250) NOT NULL,"
        " meta LONGTEXT NOT NULL, PRIMARY KEY (dir, name))",
        "CREATE TABLE IF NOT EXISTS kv"
        " (k VARBINARY(512) PRIMARY KEY, v LONGBLOB)",
        "REPLACE INTO {table} ({cols}) VALUES ({ph})",
    ),
    "postgres": (
        "CREATE TABLE IF NOT EXISTS filemeta ("
        " dir TEXT NOT NULL, name TEXT NOT NULL, meta TEXT NOT NULL,"
        " PRIMARY KEY (dir, name))",
        "CREATE TABLE IF NOT EXISTS kv (k BYTEA PRIMARY KEY, v BYTEA)",
        "INSERT INTO {table} ({cols}) VALUES ({ph})"
        " ON CONFLICT ({pk}) DO UPDATE SET {assign}",
    ),
}

_UPSERT_META = {  # per-table ON CONFLICT pieces for the postgres template
    "filemeta": ("dir, name", "meta = EXCLUDED.meta"),
    "kv": ("k", "v = EXCLUDED.v"),
}


def _guess_dialect(driver: str) -> str:
    d = driver.lower()
    if "mysql" in d or "maria" in d:
        return "mysql"
    if "psycopg" in d or d in ("pg8000", "pgdb"):
        return "postgres"
    return "sqlite"


class AbstractSqlStore(FilerStore):
    """All six FilerStore ops + KV expressed as dialect-parameterized SQL.

    Subclasses / callers provide `conn` (DB-API connection), `paramstyle`
    (qmark/format/pyformat), and `dialect` (sqlite/mysql/postgres) picking
    the DDL + upsert flavor.
    """

    def __init__(self, conn, paramstyle: str = "qmark", dialect: str = "sqlite"):
        if paramstyle not in _PLACEHOLDER:
            raise ValueError(
                f"unsupported DB-API paramstyle {paramstyle!r}; "
                f"supported: {sorted(_PLACEHOLDER)}"
            )
        if dialect not in _DIALECTS:
            raise ValueError(
                f"unsupported SQL dialect {dialect!r}; "
                f"supported: {sorted(_DIALECTS)}"
            )
        self._db = conn
        self._ph = _PLACEHOLDER[paramstyle]
        self._dialect = dialect
        self._lock = threading.RLock()
        self._create_tables()

    # -- dialect hooks ------------------------------------------------------
    def _create_tables(self) -> None:
        meta_ddl, kv_ddl, _ = _DIALECTS[self._dialect]
        with self._lock:
            cur = self._db.cursor()
            cur.execute(meta_ddl)
            cur.execute(kv_ddl)
            self._db.commit()

    def _upsert_sql(self, table: str, cols: str, nvals: int) -> str:
        pk, assign = _UPSERT_META[table]
        return _DIALECTS[self._dialect][2].format(
            table=table,
            cols=cols,
            ph=",".join([self._ph] * nvals),
            pk=pk,
            assign=assign,
        )

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        path = _norm(path)
        if path == "/":
            return "", "/"
        d, _, name = path.rpartition("/")
        return d or "/", name

    def _exec(self, sql: str, params: tuple = ()):
        cur = self._db.cursor()
        cur.execute(sql, params)
        return cur

    # -- entries ------------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        d, name = self._split(entry.full_path)
        with self._lock:
            self._exec(
                self._upsert_sql("filemeta", "dir, name, meta", 3),
                (d, name, json.dumps(entry.to_dict())),
            )
            self._db.commit()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        d, name = self._split(path)
        with self._lock:
            row = self._exec(
                f"SELECT meta FROM filemeta WHERE dir={self._ph} AND name={self._ph}",
                (d, name),
            ).fetchone()
        if row is None:
            raise NotFoundError(path)
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        with self._lock:
            self._exec(
                f"DELETE FROM filemeta WHERE dir={self._ph} AND name={self._ph}",
                (d, name),
            )
            self._db.commit()

    def delete_folder_children(self, path: str) -> None:
        p = _norm(path)
        with self._lock:
            self._exec(f"DELETE FROM filemeta WHERE dir={self._ph}", (p,))
            self._exec(
                f"DELETE FROM filemeta WHERE dir LIKE {self._ph}",
                (p.rstrip("/") + "/%",),
            )
            self._db.commit()

    def list_entries(
        self, dir_path: str, start_after: str = "", limit: int = 1000
    ) -> Iterator[Entry]:
        d = _norm(dir_path)
        with self._lock:
            rows = self._exec(
                f"SELECT meta FROM filemeta WHERE dir={self._ph} "
                f"AND name>{self._ph} ORDER BY name LIMIT {self._ph}",
                (d, start_after, limit),
            ).fetchall()
        for (meta,) in rows:
            yield Entry.from_dict(json.loads(meta))

    # -- kv -----------------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._exec(self._upsert_sql("kv", "k, v", 2), (key, value))
            self._db.commit()

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._exec(
                f"SELECT v FROM kv WHERE k={self._ph}", (key,)
            ).fetchone()
        return row[0] if row else None

    def kv_delete(self, key: bytes) -> None:
        with self._lock:
            self._exec(f"DELETE FROM kv WHERE k={self._ph}", (key,))
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()


class SqliteStore(AbstractSqlStore):
    """Embedded instance of the abstract store — the filer's default,
    standing in for the reference's leveldb default."""

    def __init__(self, db_path: str = ":memory:"):
        import sqlite3

        super().__init__(
            sqlite3.connect(db_path, check_same_thread=False),
            paramstyle="qmark",
        )


class GenericSqlStore(AbstractSqlStore):
    """Adapter for external DB-API drivers selected by dotted module name.

    filer.toml:
        [sql]
        enabled = true
        driver = "pymysql"            # any DB-API module on sys.path
        # dialect = "mysql"           # optional; guessed from the driver
        # connect kwargs passed through (host/port/user/password/database…)
    """

    def __init__(self, driver: str, dialect: str = "", **connect_kwargs):
        import importlib

        mod = importlib.import_module(driver)
        conn = mod.connect(**connect_kwargs)
        super().__init__(
            conn,
            paramstyle=getattr(mod, "paramstyle", "qmark"),
            dialect=dialect or _guess_dialect(driver),
        )
