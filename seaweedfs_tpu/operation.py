"""Client operations library: assign / upload / lookup / delete / submit.

Mirrors `weed/operation/` (assign_file_id.go:36, upload_content.go:68,
lookup.go, delete_content.go:32, submit.go:41): the primitives every gateway
and CLI tool builds on, over the master + volume server HTTP surfaces.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from .server.http_util import http_bytes, http_json
from .storage.file_id import FileId


@dataclass
class Assignment:
    fid: str
    url: str
    public_url: str
    count: int
    replicas: list[str] = field(default_factory=list)
    auth: str = ""  # fid-scoped write JWT from the master (jwt.go GenJwt)


def assign(
    master: str,
    count: int = 1,
    replication: str = "",
    collection: str = "",
    ttl: str = "",
    data_center: str = "",
) -> Assignment:
    q = f"count={count}&replication={replication}&collection={collection}&ttl={ttl}&dataCenter={data_center}"
    r = http_json("POST", f"http://{master}/dir/assign?{q}")
    if r.get("error"):
        raise RuntimeError(f"assign: {r['error']}")
    return Assignment(
        fid=r["fid"],
        url=r["url"],
        public_url=r.get("publicUrl", r["url"]),
        count=r.get("count", count),
        replicas=r.get("replicas", []),
        auth=r.get("auth", ""),
    )


def upload_data(
    url: str,
    fid: str,
    data: bytes,
    name: str = "",
    mime: str = "",
    ttl: str = "",
    jwt: str = "",
    compress: bool = True,
    is_chunk_manifest: bool = False,
) -> dict:
    # client-side auto-gzip by file type (upload_content.go:107-136); the
    # volume server stores the compressed bytes with FLAG_IS_COMPRESSED
    gzipped = False
    if compress:
        from .util import compression

        if compression.should_gzip(name, mime, data):
            gz = compression.maybe_gzip_data(data)
            if gz is not data:  # identity means it didn't pay off
                data, gzipped = gz, True

    import json

    from .server.http_util import http_bytes_headers

    q = f"?ttl={ttl}" if ttl else ""
    headers = {}
    if gzipped:
        headers["Content-Encoding"] = "gzip"
    if is_chunk_manifest:
        headers["X-Sweed-Chunk-Manifest"] = "true"
    if name:
        headers["X-Sweed-Name"] = name
    if mime:
        headers["X-Sweed-Mime"] = mime
    if jwt:
        headers["Authorization"] = f"Bearer {jwt}"
    status, body, _ = http_bytes_headers(
        "POST", f"http://{url}/{fid}{q}", body=data, timeout=60,
        headers=headers, idempotent=True,  # same fid+bytes = no-op overwrite
    )
    if status >= 300:
        raise RuntimeError(f"upload {fid}: HTTP {status} {body[:200]!r}")
    return json.loads(body or b"{}")


class LookupCache:
    """vid → locations with TTL (operation/lookup.go cache)."""

    def __init__(self, master: str, ttl_seconds: float = 600.0):
        self.master = master
        self.ttl = ttl_seconds
        self._cache: dict[int, tuple[float, list[dict]]] = {}

    def lookup(self, vid: int) -> list[dict]:
        now = time.time()
        hit = self._cache.get(vid)
        if hit and now - hit[0] < self.ttl:
            return hit[1]
        r = http_json("GET", f"http://{self.master}/dir/lookup?volumeId={vid}")
        locs = r.get("locations", [])
        if locs:
            self._cache[vid] = (now, locs)
        return locs

    def invalidate(self, vid: int) -> None:
        self._cache.pop(vid, None)


def lookup(master: str, vid: int) -> list[dict]:
    r = http_json("GET", f"http://{master}/dir/lookup?volumeId={vid}")
    return r.get("locations", [])


def download(master: str, fid: str, jwt_read_key: str = "") -> bytes:
    file_id = FileId.parse(fid)
    locs = lookup(master, file_id.volume_id)
    if not locs:
        raise RuntimeError(f"volume {file_id.volume_id} not found")
    from .security import read_auth_query

    auth = read_auth_query(jwt_read_key, fid)
    last_err = None
    for loc in locs:
        status, data = http_bytes("GET", f"http://{loc['url']}/{fid}{auth}")
        if status == 200:
            return data
        last_err = f"{loc['url']}: {status}"
    raise RuntimeError(f"download {fid}: {last_err}")


def delete_file(master: str, fid: str, jwt_key: str = "") -> bool:
    file_id = FileId.parse(fid)
    locs = lookup(master, file_id.volume_id)
    auth = ""
    if jwt_key:
        # deleting clients sharing security.toml sign their own fid token
        from .security import gen_jwt

        auth = "?auth=" + gen_jwt(jwt_key, fid)
    for loc in locs:
        status, _ = http_bytes("DELETE", f"http://{loc['url']}/{fid}{auth}")
        if status < 300:
            return True
    return False


def delete_files(master: str, fids: list[str], jwt_key: str = "") -> int:
    """Grouped deletion (delete_content.go:32): fids are grouped by volume
    and each group goes to every replica location as ONE /_batch_delete
    request — the BatchDelete fan-out the reference's DeleteFiles client
    does, instead of a round-trip per fid. Returns the deleted count."""
    from collections import defaultdict

    by_vid: dict[int, list[str]] = defaultdict(list)
    for fid in fids:
        try:
            by_vid[FileId.parse(fid).volume_id].append(fid)
        except Exception:  # sweedlint: ok broad-except unparseable fids just don't count toward the delete set
            pass
    deleted: set[str] = set()
    for vid, group in by_vid.items():
        locs = lookup(master, vid)
        auths = {}
        if jwt_key:
            from .security import gen_jwt

            auths = {fid: gen_jwt(jwt_key, fid) for fid in group}
        for loc in locs:
            try:
                r = http_json(
                    "POST",
                    f"http://{loc['url']}/_batch_delete",
                    {"fids": group, "auths": auths},
                )
            except Exception:  # sweedlint: ok broad-except one unreachable replica; the others still count
                continue
            for item in r.get("results", []):
                if item.get("status") == 202:
                    deleted.add(item["fid"])
                elif item.get("status") == 409:
                    # chunk manifest: the single-fid path cascades its
                    # data-chunk deletes (delete_content.go does the same
                    # manifest special-case client-side)
                    if delete_file(master, item["fid"], jwt_key=jwt_key):
                        deleted.add(item["fid"])
    return len(deleted)


def submit(
    master: str,
    data: bytes,
    name: str = "",
    mime: str = "",
    replication: str = "",
    collection: str = "",
    ttl: str = "",
    max_mb: int = 0,
) -> str:
    """Assign + upload in one call (submit.go:41). Returns the fid.

    With max_mb > 0, files past the limit are split into chunk needles
    plus a manifest needle the volume server resolves on read
    (submit.go:115 upload_chunked_file + operation/chunked_file.go) —
    large objects without a filer in the path."""
    if max_mb > 0 and len(data) > max_mb * 1024 * 1024:
        return _submit_chunked(
            master, data, name, mime, replication, collection, ttl,
            max_mb * 1024 * 1024,
        )
    a = assign(
        master, replication=replication, collection=collection, ttl=ttl
    )
    upload_data(a.url, a.fid, data, name=name, mime=mime, ttl=ttl, jwt=a.auth)
    return a.fid


def _submit_chunked(
    master: str,
    data: bytes,
    name: str,
    mime: str,
    replication: str,
    collection: str,
    ttl: str,
    chunk_size: int,
) -> str:
    import json

    chunks = []
    try:
        for off in range(0, len(data), chunk_size):
            piece = data[off : off + chunk_size]
            a = assign(
                master, replication=replication, collection=collection,
                ttl=ttl,
            )
            # chunk bytes go up verbatim: the manifest read path
            # concatenates stored bytes, so per-chunk compression would
            # corrupt the stream
            upload_data(
                a.url, a.fid, piece, ttl=ttl, jwt=a.auth, compress=False
            )
            chunks.append({"fid": a.fid, "offset": off, "size": len(piece)})
        manifest = json.dumps(
            {"name": name, "mime": mime, "size": len(data), "chunks": chunks}
        ).encode()
        a = assign(
            master, replication=replication, collection=collection, ttl=ttl
        )
        upload_data(
            a.url, a.fid, manifest, name=name, mime=mime, ttl=ttl,
            jwt=a.auth, compress=False, is_chunk_manifest=True,
        )
        return a.fid
    except Exception:
        # no fid reaches the caller, so already-uploaded chunks would be
        # unreferenced garbage forever — sweep them (submit.go cleanup)
        if chunks:
            try:
                delete_files(master, [c["fid"] for c in chunks])
            except Exception:  # sweedlint: ok broad-except best-effort GC; the original upload error matters more
                pass
        raise
