"""Minimal XML (de)serialization for the S3 wire protocol."""

from __future__ import annotations

import xml.etree.ElementTree as ET

from ..util.safe_xml import safe_fromstring
from typing import Any

S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _build(parent: ET.Element, value: Any) -> None:
    if isinstance(value, dict):
        for k, v in value.items():
            if isinstance(v, list):
                for item in v:
                    child = ET.SubElement(parent, k)
                    _build(child, item)
            else:
                child = ET.SubElement(parent, k)
                _build(child, v)
    elif isinstance(value, bool):
        parent.text = "true" if value else "false"
    elif value is None:
        parent.text = ""
    else:
        parent.text = str(value)


def to_xml(root_tag: str, value: Any, xmlns: str = S3_XMLNS) -> bytes:
    root = ET.Element(root_tag)
    if xmlns:
        root.set("xmlns", xmlns)
    _build(root, value)
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def error_xml(code: str, message: str, resource: str = "") -> bytes:
    return to_xml(
        "Error",
        {"Code": code, "Message": message, "Resource": resource},
        xmlns="",
    )


def strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def parse_xml(body: bytes) -> ET.Element:
    return safe_fromstring(body)


def findall(el: ET.Element, tag: str) -> list[ET.Element]:
    return [c for c in el.iter() if strip_ns(c.tag) == tag]


def find_text(el: ET.Element, tag: str, default: str = "") -> str:
    for c in el.iter():
        if strip_ns(c.tag) == tag:
            return c.text or default
    return default
