"""Minimal S3 client with AWS SigV4 signing.

Used by tests and as a convenience library (the reference relies on the AWS
SDKs for this — `test/s3/basic/basic_test.go`). The signing code here is an
independent implementation of the SigV4 spec (canonical request → string to
sign → HMAC chain) so that client and server don't share the same bug.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
import urllib.request
from datetime import datetime, timezone
from typing import Optional

from ..util.parsers import tolerant_uint
from .xml_util import find_text, parse_xml, to_xml


class S3Client:
    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        ssl_context=None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        # https endpoints: pinned CA and/or client cert (security/tls.py
        # client_context); None = system defaults for https, n/a for http
        self.ssl_context = ssl_context

    # -- SigV4 ---------------------------------------------------------------
    def _sign(
        self, method: str, path: str, query: dict, headers: dict, body: bytes
    ) -> dict:
        if not self.access_key:
            return headers
        now = datetime.now(tz=timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.endpoint).netloc
        headers = dict(headers)
        # streaming uploads pre-set the payload marker; don't overwrite it
        payload_hash = headers.get(
            "X-Amz-Content-Sha256", hashlib.sha256(body).hexdigest()
        )
        headers["Host"] = host
        headers["X-Amz-Date"] = amz_date
        headers["X-Amz-Content-Sha256"] = payload_hash
        signed = sorted(k.lower() for k in headers)
        canonical_headers = "".join(
            f"{k}:{' '.join(str(headers[h]).split())}\n"
            for k, h in sorted((k.lower(), k) for k in headers)
        )
        canonical_query = "&".join(
            urllib.parse.quote(k, safe="~-._")
            + "="
            + urllib.parse.quote(str(v), safe="~-._")
            for k, v in sorted(query.items())
        )
        canonical = "\n".join(
            [
                method,
                urllib.parse.quote(path, safe="/~-._"),
                canonical_query,
                canonical_headers,
                ";".join(signed),
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )

        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        key = h(
            h(
                h(h(("AWS4" + self.secret_key).encode(), datestamp), self.region),
                "s3",
            ),
            "aws4_request",
        )
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        )
        return headers

    def presign(self, method: str, path: str, expires: int = 3600) -> str:
        """Presigned URL (query-string auth)."""
        now = datetime.now(tz=timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.endpoint).netloc
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        query = {
            "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
            "X-Amz-Credential": f"{self.access_key}/{scope}",
            "X-Amz-Date": amz_date,
            "X-Amz-Expires": str(expires),
            "X-Amz-SignedHeaders": "host",
        }
        canonical_query = "&".join(
            urllib.parse.quote(k, safe="~-._")
            + "="
            + urllib.parse.quote(v, safe="~-._")
            for k, v in sorted(query.items())
        )
        canonical = "\n".join(
            [
                method,
                urllib.parse.quote(path, safe="/~-._"),
                canonical_query,
                f"host:{host}\n",
                "host",
                "UNSIGNED-PAYLOAD",
            ]
        )
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )

        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        key = h(
            h(
                h(h(("AWS4" + self.secret_key).encode(), datestamp), self.region),
                "s3",
            ),
            "aws4_request",
        )
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        return (
            f"{self.endpoint}{urllib.parse.quote(path, safe='/~-._')}"
            f"?{canonical_query}&X-Amz-Signature={sig}"
        )

    # -- transport -----------------------------------------------------------
    def request(
        self,
        method: str,
        path: str,
        query: Optional[dict] = None,
        body: bytes = b"",
        headers: Optional[dict] = None,
    ) -> tuple[int, bytes, dict]:
        query = query or {}
        headers = self._sign(method, path, query, headers or {}, body)
        qs = urllib.parse.urlencode(query)
        url = (
            self.endpoint
            + urllib.parse.quote(path, safe="/~-._")
            + ("?" + qs if qs else "")
        )
        if url.startswith("http://"):
            # plain-http endpoints ride the pooled keep-alive transport;
            # https keeps urllib for this client's custom ssl_context
            from ..server.http_util import http_bytes_headers

            return http_bytes_headers(
                method, url, body=body if body else None,
                timeout=30, headers=headers,
            )
        req = urllib.request.Request(
            url, data=body if body else None, method=method, headers=headers
        )
        try:
            # sweedlint: ok deadline-not-propagated remote-S3 egress; a signed third-party request must not carry the internal deadline header
            with urllib.request.urlopen(req, timeout=30, context=self.ssl_context) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def put_object_streaming(
        self, bucket: str, key: str, chunks: list[bytes]
    ) -> tuple[int, bytes, dict]:
        """Streaming SigV4 upload: aws-chunked framing with the per-chunk
        signature chain seeded by the header signature."""
        path = f"/{bucket}/{key}"
        total = sum(len(c) for c in chunks)
        headers = self._sign(
            "PUT",
            path,
            {},
            {
                "X-Amz-Content-Sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
                "X-Amz-Decoded-Content-Length": str(total),
            },
            b"",
        )
        seed = headers["Authorization"].rsplit("Signature=", 1)[1]
        scope = headers["Authorization"].split("Credential=")[1].split(",")[0]
        scope = scope.split("/", 1)[1]
        amz_date = headers["X-Amz-Date"]
        date, region = scope.split("/")[0], scope.split("/")[1]

        def hm(k, m):
            return hmac.new(k, m.encode(), hashlib.sha256).digest()

        key_b = hm(
            hm(hm(hm(("AWS4" + self.secret_key).encode(), date), region), "s3"),
            "aws4_request",
        )
        empty = hashlib.sha256(b"").hexdigest()
        prev = seed
        framed = bytearray()
        for c in list(chunks) + [b""]:
            sts = "\n".join(
                [
                    "AWS4-HMAC-SHA256-PAYLOAD",
                    amz_date,
                    scope,
                    prev,
                    empty,
                    hashlib.sha256(c).hexdigest(),
                ]
            )
            prev = hmac.new(key_b, sts.encode(), hashlib.sha256).hexdigest()
            framed += f"{len(c):x};chunk-signature={prev}\r\n".encode()
            framed += c + b"\r\n"
        url = self.endpoint + urllib.parse.quote(path, safe="/~-._")
        if url.startswith("http://"):
            from ..server.http_util import http_bytes_headers

            return http_bytes_headers(
                "PUT", url, body=bytes(framed), timeout=30, headers=headers
            )
        req = urllib.request.Request(
            url, data=bytes(framed), method="PUT", headers=headers
        )
        try:
            # sweedlint: ok deadline-not-propagated remote-S3 egress; a signed third-party request must not carry the internal deadline header
            with urllib.request.urlopen(req, timeout=30, context=self.ssl_context) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    # -- multipart upload (for large-object streaming without buffering) -----
    def initiate_multipart(self, bucket: str, key: str) -> str:
        status, body, _ = self.request("POST", f"/{bucket}/{key}", query={"uploads": ""})
        if status != 200:
            raise RuntimeError(f"initiate multipart: HTTP {status}")
        import re as _re

        m = _re.search(rb"<UploadId>([^<]+)</UploadId>", body)
        if not m:
            raise RuntimeError("initiate multipart: no UploadId in response")
        return m.group(1).decode()

    def upload_part(
        self, bucket: str, key: str, upload_id: str, part_number: int, body: bytes
    ) -> str:
        status, _, headers = self.request(
            "PUT",
            f"/{bucket}/{key}",
            query={"partNumber": str(part_number), "uploadId": upload_id},
            body=body,
        )
        if status != 200:
            raise RuntimeError(f"upload part {part_number}: HTTP {status}")
        return headers.get("ETag", headers.get("Etag", "")).strip('"')

    def complete_multipart(
        self, bucket: str, key: str, upload_id: str, parts: list[tuple[int, str]]
    ):
        xml = "<CompleteMultipartUpload>"
        for num, etag in parts:
            xml += f"<Part><PartNumber>{num}</PartNumber><ETag>{etag}</ETag></Part>"
        xml += "</CompleteMultipartUpload>"
        return self.request(
            "POST",
            f"/{bucket}/{key}",
            query={"uploadId": upload_id},
            body=xml.encode(),
        )

    def abort_multipart(self, bucket: str, key: str, upload_id: str):
        return self.request(
            "DELETE", f"/{bucket}/{key}", query={"uploadId": upload_id}
        )

    def put_object_from_file(
        self, bucket: str, key: str, path: str, part_bytes: int = 64 * 1024 * 1024
    ) -> int:
        """Upload a file of any size with bounded memory: single PUT when it
        fits one part, multipart otherwise. Returns the final HTTP status."""
        import os as _os

        size = _os.path.getsize(path)
        with open(path, "rb") as f:
            if size <= part_bytes:
                status, _, _ = self.put_object(bucket, key, f.read())
                return status
            upload_id = self.initiate_multipart(bucket, key)
            try:
                parts: list[tuple[int, str]] = []
                num = 1
                while True:
                    chunk = f.read(part_bytes)
                    if not chunk:
                        break
                    parts.append(
                        (num, self.upload_part(bucket, key, upload_id, num, chunk))
                    )
                    num += 1
                status, _, _ = self.complete_multipart(bucket, key, upload_id, parts)
                return status
            except Exception:
                # don't strand uploaded parts on the backend
                try:
                    self.abort_multipart(bucket, key, upload_id)
                except Exception:  # sweedlint: ok broad-except best-effort abort; the complete error re-raises below
                    pass
                raise

    def get_object_to_file(
        self, bucket: str, key: str, path: str, part_bytes: int = 64 * 1024 * 1024
    ) -> int:
        """Ranged-GET download with bounded memory; returns total bytes."""
        status, _, headers = self.head_object(bucket, key)
        if status != 200:
            raise RuntimeError(f"head before ranged get: HTTP {status}")
        size = tolerant_uint(headers.get("Content-Length", 0), 0)
        total = 0
        with open(path, "wb") as f:
            while total < size:
                end = min(total + part_bytes, size) - 1
                status, data, _ = self.get_object(bucket, key, rng=f"bytes={total}-{end}")
                if status not in (200, 206) or not data:
                    raise RuntimeError(f"ranged get at {total}: HTTP {status}")
                f.write(data)
                total += len(data)
        return total

    # -- convenience ops -----------------------------------------------------
    def create_bucket(self, bucket: str):
        return self.request("PUT", f"/{bucket}")

    def delete_bucket(self, bucket: str):
        return self.request("DELETE", f"/{bucket}")

    def list_buckets(self):
        return self.request("GET", "/")

    def put_object(self, bucket: str, key: str, body: bytes, **headers):
        return self.request("PUT", f"/{bucket}/{key}", body=body, headers=headers)

    def get_object(self, bucket: str, key: str, rng: str = ""):
        h = {"Range": rng} if rng else {}
        return self.request("GET", f"/{bucket}/{key}", headers=h)

    def head_object(self, bucket: str, key: str):
        return self.request("HEAD", f"/{bucket}/{key}")

    def delete_object(self, bucket: str, key: str):
        return self.request("DELETE", f"/{bucket}/{key}")

    def list_objects(self, bucket: str, v2: bool = False, **params):
        if v2:
            params["list-type"] = "2"
        return self.request("GET", f"/{bucket}", query=params)

    def select_object_content(
        self,
        bucket: str,
        key: str,
        expression: str,
        input_format: str = "csv",
        compression: str = "NONE",
        output_format: str = "",
        request_progress: bool = False,
    ) -> tuple[bytes, dict]:
        """SelectObjectContent: POST ?select&select-type=2, decode the
        event stream (CRC-verified) → (records_bytes, stats_dict).
        S3 errors raise IOError carrying the error code."""
        in_ser: dict = {"CompressionType": compression}
        if input_format == "csv":
            in_ser["CSV"] = {"FileHeaderInfo": "USE"}
        else:
            in_ser["JSON"] = {"Type": "LINES"}
        out_fmt = output_format or input_format
        out_ser = {"CSV": {}} if out_fmt == "csv" else {"JSON": {}}
        req: dict = {
            "Expression": expression,
            "ExpressionType": "SQL",
            "InputSerialization": in_ser,
            "OutputSerialization": out_ser,
        }
        if request_progress:
            req["RequestProgress"] = {"Enabled": True}
        body = to_xml("SelectObjectContentRequest", req, xmlns="")
        status, data, _ = self.request(
            "POST",
            f"/{bucket}/{key}",
            query={"select": "", "select-type": "2"},
            body=body,
            headers={"Content-Type": "application/xml"},
        )
        if status != 200:
            code = find_text(parse_xml(data), "Code", "InternalError")
            raise IOError(f"select {bucket}/{key}: {code} (HTTP {status})")
        from ..query.select import iter_events

        records, stats = [], {}
        for ev in iter_events(data):
            etype = ev["headers"].get(":event-type", "")
            if etype == "Records":
                records.append(ev["payload"])
            elif etype == "Stats":
                sx = parse_xml(ev["payload"])
                stats = {
                    t: tolerant_uint(find_text(sx, t, "0"), 0)
                    for t in (
                        "BytesScanned", "BytesProcessed", "BytesReturned"
                    )
                }
        return b"".join(records), stats
