"""S3-compatible gateway over the filer (reference: `weed/s3api/`).

Buckets are directories under `/buckets`; objects proxy to the filer;
multipart uploads assemble chunk lists server-side without copying data
(`filer_multipart.go`). Authentication implements AWS Signature V4 (header,
presigned-query, and streaming-chunked flavors) plus legacy V2.
"""

from .s3api_server import S3ApiServer  # noqa: F401
from .auth import IAM, Identity  # noqa: F401
