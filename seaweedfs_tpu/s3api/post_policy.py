"""S3 POST policy uploads — browser form uploads with signed policies.

Reference: `weed/s3api/s3api_object_handlers_postpolicy.go:20`
(PostPolicyBucketHandler), `weed/s3api/policy/postpolicyform.go`
(ParsePostPolicyForm / CheckPostPolicy), and the policy-signature checks in
`s3api_object_handlers_postpolicy.go:235-300`
(doesPolicySignatureMatch, V2 + V4 forms).

Flow (AWS sigv4-HTTPPOSTConstructPolicy): the server hands a client a
base64 policy document + a signature over it; the browser POSTs
multipart/form-data to the bucket URL carrying policy, signature,
credential fields, and the file. The server re-signs the policy with the
credential's secret, compares, then validates every form field against the
policy's conditions (eq / starts-with / content-length-range) and the
expiration.
"""

from __future__ import annotations

import base64
import binascii
import email.parser
import email.policy
import hashlib
import hmac
import json
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Optional


def parse_multipart_form(
    body: bytes, content_type: str
) -> tuple[dict[str, str], bytes, str]:
    """(form_values, file_bytes, file_name) from a multipart/form-data body.

    Field names are case-insensitive in the reference (http.Header); values
    keep their case. The `file` part must be last per the AWS spec — fields
    after it are ignored, like S3 does.
    """
    parser = email.parser.BytesParser(policy=email.policy.HTTP)
    msg = parser.parsebytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body
    )
    if not msg.is_multipart():
        raise ValueError("not a multipart form")
    values: dict[str, str] = {}
    file_bytes: Optional[bytes] = None
    file_name = ""
    for part in msg.iter_parts():
        name = part.get_param("name", header="content-disposition")
        if name is None:
            continue
        if name.lower() == "file":
            file_bytes = part.get_payload(decode=True) or b""
            file_name = part.get_filename() or ""
            break  # AWS ignores fields after the file part
        payload = part.get_payload(decode=True) or b""
        values[name.lower()] = payload.decode("utf-8", "replace")
    if file_bytes is None:
        raise FileNotFoundError("POST form has no file part")
    return values, file_bytes, file_name


@dataclass
class PostPolicy:
    expiration: Optional[datetime] = None
    # conditions keyed by lowercased field name (no $): (match_type, value)
    conditions: dict[str, tuple[str, str]] = field(default_factory=dict)
    length_min: int = -1
    length_max: int = -1


def parse_post_policy(policy_json: str) -> PostPolicy:
    """postpolicyform.go ParsePostPolicyForm: strict shape validation."""
    try:
        doc = json.loads(policy_json)
    except json.JSONDecodeError as e:
        raise ValueError(f"policy is not JSON: {e}")
    out = PostPolicy()
    exp = doc.get("expiration")
    if exp is not None:
        try:
            out.expiration = datetime.fromisoformat(
                exp.replace("Z", "+00:00")
            )
        except ValueError:
            raise ValueError(f"bad expiration {exp!r}")
    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            # {"bucket": "x"} is shorthand for ["eq", "$bucket", "x"]
            for k, v in cond.items():
                out.conditions[str(k).lower()] = ("eq", str(v))
            continue
        if not isinstance(cond, list) or not cond:
            raise ValueError(f"bad condition {cond!r}")
        op = str(cond[0]).lower()
        if op == "content-length-range":
            if len(cond) != 3:
                raise ValueError("content-length-range needs [op, min, max]")
            out.length_min, out.length_max = int(cond[1]), int(cond[2])
            continue
        if op not in ("eq", "starts-with") or len(cond) != 3:
            raise ValueError(f"unsupported condition {cond!r}")
        key = str(cond[1])
        if not key.startswith("$"):
            raise ValueError(f"condition key must start with $: {key!r}")
        out.conditions[key[1:].lower()] = (op, str(cond[2]))
    return out


# form fields that need not be declared as policy conditions ("bucket" is
# URL-derived — the gateway injects it into values — not a browser field)
_NO_DECLARE = {
    "policy", "x-amz-signature", "file", "awsaccesskeyid", "signature",
    "x-amz-credential", "x-amz-algorithm", "x-amz-date", "bucket",
}
# declared conditions that are validated elsewhere (signature plumbing);
# NOT "bucket" — a signed ["eq", "$bucket", ...] must bind the form to that
# bucket or the signature could be replayed against another bucket
_SKIP_CHECK = _NO_DECLARE - {"bucket"}


def check_post_policy(values: dict[str, str], policy: PostPolicy) -> None:
    """CheckPostPolicy (postpolicyform.go): expiration + every policy
    condition must hold against the form values, AND every non-exempt form
    field must be declared in the conditions (a field the signer never
    authorized — success_action_redirect, content-type, … — is rejected,
    matching AWS/minio semantics). Raises ValueError."""
    if policy.expiration is not None:
        now = datetime.now(timezone.utc)
        exp = policy.expiration
        if exp.tzinfo is None:
            exp = exp.replace(tzinfo=timezone.utc)
        if now > exp:
            raise ValueError("policy expired")
    for key, (op, want) in policy.conditions.items():
        if key in _SKIP_CHECK or key == "content-length-range":
            continue
        got = values.get(key)
        if got is None:
            # the reference tolerates policy conditions on fields that the
            # form omits only for x-amz-meta-*; everything else must match
            if key.startswith("x-amz-meta-"):
                continue
            raise ValueError(f"form is missing policy field {key!r}")
        if op == "eq" and got != want:
            raise ValueError(f"{key}: {got!r} != {want!r}")
        if op == "starts-with" and not got.startswith(want):
            raise ValueError(f"{key}: {got!r} !startswith {want!r}")
    for key in values:
        if key in _NO_DECLARE or key.startswith("x-ignore-"):
            continue
        if key not in policy.conditions:
            raise ValueError(f"form field {key!r} not declared in policy")


def verify_policy_signature_v4(
    values: dict[str, str], secret_for_access_key
) -> Optional[str]:
    """doesPolicySignatureV4Match: HMAC chain over the base64 policy.
    Returns the access key on success, None on mismatch."""
    from .auth import IAM

    cred = values.get("x-amz-credential", "")
    parts = cred.split("/")
    if len(parts) != 5:
        return None
    access_key, date, region, service, _ = parts
    secret = secret_for_access_key(access_key)
    if secret is None:
        return None
    key = IAM.signing_key(secret, date, region, service)
    want = hmac.new(
        key, values.get("policy", "").encode(), hashlib.sha256
    ).hexdigest()
    given = values.get("x-amz-signature", "")
    return access_key if hmac.compare_digest(want, given) else None


def verify_policy_signature_v2(
    values: dict[str, str], secret_for_access_key
) -> Optional[str]:
    """doesPolicySignatureV2Match: base64(HMAC-SHA1(secret, policy))."""
    access_key = values.get("awsaccesskeyid", "")
    secret = secret_for_access_key(access_key)
    if secret is None:
        return None
    want = base64.b64encode(
        hmac.new(
            secret.encode(), values.get("policy", "").encode(), hashlib.sha1
        ).digest()
    ).decode()
    given = values.get("signature", "")
    return access_key if hmac.compare_digest(want, given) else None


def decode_policy(values: dict[str, str]) -> str:
    try:
        return base64.b64decode(values.get("policy", "")).decode()
    except (binascii.Error, UnicodeDecodeError) as e:
        raise ValueError(f"bad policy encoding: {e}")
