"""S3-compatible REST server over the filer.

Mirrors `weed/s3api/s3api_server.go:38` (router) and its handler files:
bucket CRUD (= dirs under `/buckets`, `s3api_bucket_handlers.go`), object
CRUD proxied to the filer (`s3api_object_handlers.go`), multipart uploads
assembled by chunk-list concatenation without data copy
(`filer_multipart.go`), ListObjects v1/v2 (`s3api_objects_list_handlers.go`),
object tagging (`s3api_object_tagging_handlers.go`), and multi-object delete.

Requests are authenticated by `auth.IAM` (SigV4 header/presigned/streaming +
SigV2) and authorized per identity action grants (`auth_credentials.go:124`).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
import uuid
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler

from ..server.http_util import (
    CountedReader,
    has_dot_segments,
    drain_refused_body,
    parse_content_length,
    relay_stream,
    start_server,
)
from ..stats import trace as _trace
from ..stats.metrics import default_registry
from ..util.parsers import parse_ascii_uint
from ..util.pipeline import BoundedExecutor, prefetch_iter
from . import auth as s3auth
from . import policy_engine as pe
from . import post_policy as pp
from .auth import IAM
from ..filer.client import FilerClient
from .xml_util import error_xml, find_text, findall, parse_xml, to_xml

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = "/buckets/.uploads"
TAG_PREFIX = "X-Amz-Tag-"

_ERR_STATUS = {
    "NoSuchBucket": 404,
    "NoSuchKey": 404,
    "NoSuchUpload": 404,
    "AccessDenied": 403,
    "SignatureDoesNotMatch": 403,
    "InvalidAccessKeyId": 403,
    "ExpiredPresignRequest": 403,
    "MissingFields": 400,
    "MalformedXML": 400,
    "IncompleteBody": 400,
    "InvalidPart": 400,
    "InvalidArgument": 400,
    "BucketAlreadyExists": 409,
    "BucketNotEmpty": 409,
    "NoSuchBucketPolicy": 404,
    "AuthorizationHeaderMalformed": 400,
    "AuthorizationQueryParametersError": 400,
    # SelectObjectContent request rejections (query/select.py)
    "InvalidRequest": 400,
    "InvalidTextEncoding": 400,
    "InvalidExpressionType": 400,
    "InvalidCompressionFormat": 400,
    "UnsupportedSqlStructure": 400,
    "InternalError": 500,
}


def _parse_s3_int(s: str) -> int:
    """AWS-strict non-negative query integer (max-keys, partNumber):
    the shared ascii-digit parser, kept under its historical local name."""
    return parse_ascii_uint(s)


def _iso(ts: float) -> str:
    return datetime.fromtimestamp(ts, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.000Z"
    )


def _parse_copy_source(src: str) -> tuple[str, str]:
    """X-Amz-Copy-Source → (bucket, key); either may come back empty for a
    malformed header (s3api_object_copy_handlers.go pathToBucketAndObject)."""
    src = urllib.parse.unquote(src)
    sb, _, sk = src.lstrip("/").partition("/")
    return sb, sk


class S3ApiServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8333,
        filer_url: str = "127.0.0.1:8888",
        iam: IAM | None = None,
        tls_cert: str = "",
        tls_key: str = "",
        tls_ca: str = "",
    ):
        self.host, self.port = host, port
        # "host:p1,host:p2" → ring-aware routing across the filer fleet;
        # a single address stays the plain FilerClient (filer/ring.py)
        from ..filer.ring import make_client

        self.client = make_client(filer_url)
        # object/bucket op latency; op label is method × path-kind (bounded)
        self._req_hist = default_registry.histogram(
            "s3_request_seconds", "s3 gateway request latency"
        )
        self.iam = iam or IAM()
        self._policy_cache: dict = {}  # bucket → (BucketPolicy | None,)
        self._policy_lock = threading.Lock()  # handler threads race the cache
        self._policy_gen: dict = {}  # bucket → invalidation generation
        self._tls = (tls_cert, tls_key, tls_ca)
        self._srv = None

    # ---------------------------------------------------------------- helpers
    def _bucket_dir(self, bucket: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}"

    def _object_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}/{key}"

    def _bucket_exists(self, bucket: str) -> bool:
        e = self.client.get_entry(self._bucket_dir(bucket))
        return bool(e and e.get("is_directory"))

    # ---------------------------------------------------------------- service
    def _list_buckets(self, identity):
        buckets = [
            {"Name": e["name"], "CreationDate": _iso(e.get("crtime", 0))}
            for e in self.client.list(BUCKETS_DIR, limit=10000)
            if e.get("is_directory") and not e["name"].startswith(".")
        ]
        return 200, to_xml(
            "ListAllMyBucketsResult",
            {
                "Owner": {"ID": getattr(identity, "name", "") or "anonymous"},
                "Buckets": {"Bucket": buckets},
            },
        )

    # ---------------------------------------------------------------- buckets
    def _put_bucket(self, bucket):
        if self._bucket_exists(bucket):
            return _err("BucketAlreadyExists", bucket)
        self.client.mkdir(self._bucket_dir(bucket))
        return 200, b""

    def _head_bucket(self, bucket):
        if not self._bucket_exists(bucket):
            return 404, b""
        return 200, b""

    def _delete_bucket(self, bucket):
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        self.client.delete(self._bucket_dir(bucket), recursive=True)
        # the policy dies with the bucket — a recreated namesake must not
        # inherit the old grants
        self.client.delete(f"{self.POLICIES_DIR}/{bucket}")
        with self._policy_lock:
            self._policy_gen[bucket] = self._policy_gen.get(bucket, 0) + 1
            self._policy_cache.pop(bucket, None)
        return 204, b""

    # ------------------------------------------------------------ list objects
    def _iter_keys(self, dir_path, rel, prefix, marker):
        """Sorted recursive key walk with prefix/marker subtree pruning."""
        start = ""
        entries = self.client.list(dir_path, start_after=start, limit=100000)
        for e in entries:
            if rel == "" and e["name"].startswith("."):
                continue  # .uploads &co at bucket root
            key = rel + e["name"]
            if e.get("is_directory"):
                sub = key + "/"
                if prefix and not (
                    prefix.startswith(sub[: len(prefix)])
                    or sub.startswith(prefix)
                ):
                    continue
                if marker and sub <= marker and not marker.startswith(sub):
                    continue
                yield from self._iter_keys(
                    dir_path + "/" + e["name"], sub, prefix, marker
                )
            else:
                yield key, e

    def _list_objects(self, bucket, q, v2: bool):
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = _parse_s3_int(q.get("max-keys", "1000"))
        except ValueError:
            return _err("InvalidArgument", bucket,
                        "max-keys must be a non-negative integer")
        if v2:
            marker = q.get("continuation-token", "") or q.get("start-after", "")
        else:
            marker = q.get("marker", "")
        contents, common = [], []
        truncated = False
        keys_iter = (
            self._iter_keys(self._bucket_dir(bucket), "", prefix, marker)
            if max_keys > 0 else ()
            # max-keys=0 is an empty NON-truncated listing (AWS semantics);
            # entering the loop would emit IsTruncated=true with an empty
            # continuation token and trap v2 paginators in a loop
        )
        for key, e in keys_iter:
            if prefix and not key.startswith(prefix):
                continue
            if marker and key <= marker:
                continue
            if delimiter:
                idx = key.find(delimiter, len(prefix))
                if idx >= 0:
                    cp = key[: idx + len(delimiter)]
                    if marker and cp <= marker:
                        continue  # whole prefix was already returned
                    if common and common[-1] == cp:
                        continue
                    if len(contents) + len(common) >= max_keys:
                        truncated = True
                        break
                    common.append(cp)
                    continue
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            size = max(
                (c["offset"] + c["size"] for c in e.get("chunks", [])), default=0
            )
            contents.append(
                {
                    "Key": key,
                    "LastModified": _iso(e.get("mtime", 0)),
                    "ETag": f'"{e.get("extended", {}).get("md5", "")}"',
                    "Size": size,
                    "StorageClass": "STANDARD",
                }
            )
        # marker is exclusive: the next page starts after the last returned
        # key/prefix (S3 v1 NextMarker / v2 continuation semantics)
        last_key = contents[-1]["Key"] if contents else ""
        last_cp = common[-1] if common else ""
        next_marker = max(last_key, last_cp)
        result = {
            "Name": bucket,
            "Prefix": prefix,
            "MaxKeys": max_keys,
            "Delimiter": delimiter,
            "IsTruncated": truncated,
            "Contents": contents,
            "CommonPrefixes": [{"Prefix": p} for p in common],
        }
        if v2:
            result["KeyCount"] = len(contents) + len(common)
            if truncated:
                result["NextContinuationToken"] = next_marker
        else:
            result["Marker"] = marker
            if truncated:
                result["NextMarker"] = next_marker
        return 200, to_xml("ListBucketResult", result)

    # ---------------------------------------------------------------- objects
    def _put_object(self, bucket, key, headers, body):
        streamed = isinstance(body, tuple)  # (reader, length) pass-through
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        if key.endswith("/"):
            if streamed:
                body[0].drain()  # directory markers carry no meaningful body
            self.client.mkdir(self._object_path(bucket, key[:-1]))
            return 200, b"", {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'}
        src = headers.get("X-Amz-Copy-Source", "")
        if src:
            return self._copy_object(bucket, key, src)
        body, chunk_err = self._decode_chunked(headers, body, key)
        if chunk_err is not None:
            return chunk_err
        extended = {
            k.title(): v
            for k, v in headers.items()
            if k.lower().startswith("x-amz-meta-")
        }
        if streamed:
            reader, length = body
            r = self.client.put_object_stream(
                self._object_path(bucket, key), reader, length,
                content_type=headers.get("Content-Type", ""),
                extended=extended,
            )
        else:
            r = self.client.put_object(
                self._object_path(bucket, key),
                body,
                content_type=headers.get("Content-Type", ""),
                extended=extended,
            )
        return 200, b"", {"ETag": f'"{r.get("eTag", "")}"'}

    def _decode_chunked(self, headers, body, key):
        """Undo STREAMING-AWS4-HMAC-SHA256-PAYLOAD framing, verifying the
        per-chunk signature chain. Returns (body, None) — body unchanged
        when the request wasn't aws-chunked — or (None, error_response)."""
        if headers.get("X-Amz-Content-Sha256") != s3auth.STREAMING_PAYLOAD:
            return body, None
        # the streaming auth context is built OUTSIDE the framing try: a
        # ValueError here (e.g. malformed credential scope unpack) is an
        # auth/header problem and must not masquerade as IncompleteBody
        try:
            verify = self.iam.streaming_context(headers)
        except ValueError:
            return None, _err("AuthorizationHeaderMalformed", key,
                              "malformed credential scope")
        try:
            return s3auth.decode_aws_chunked(body, verify=verify), None
        except s3auth.ChunkSignatureError:
            return None, _err("SignatureDoesNotMatch", key)
        except ValueError:
            # malformed chunk framing (bad hex size / missing CRLF) must be
            # the client's 400, not an unhandled 500
            return None, _err("IncompleteBody", key)

    def _copy_object(self, bucket, key, src):
        sb, sk = _parse_copy_source(src)
        if not sb or not sk:
            return _err("InvalidCopySource", src)
        status, data, _ = self.client.get_object(self._object_path(sb, sk))
        if status != 200:
            return _err("NoSuchKey", src)
        entry = self.client.get_entry(self._object_path(sb, sk)) or {}
        dst_path = self._object_path(bucket, key)
        r = self.client.put_object(
            dst_path, data, content_type=entry.get("mime", "")
        )
        # S3's default COPY directive carries user metadata + tags along
        src_ext = {
            k: v for k, v in entry.get("extended", {}).items() if k != "md5"
        }
        if src_ext:
            dst = self.client.get_entry(dst_path)
            if dst is not None:
                dst["extended"] = src_ext | {"md5": dst["extended"].get("md5", "")}
                self.client.create_entry(dst_path, dst)
        return 200, to_xml(
            "CopyObjectResult",
            {"ETag": f'"{r.get("eTag", "")}"', "LastModified": _iso(time.time())},
        )

    def _get_acl(self, bucket, key=None):
        """Canned owner/FULL_CONTROL ACL for bucket and object ?acl probes.
        The reference leaves ACL routes unimplemented (s3api_server.go:
        108-117, commented out); SDKs that probe ACLs (boto3, rclone) still
        need a well-formed AccessControlPolicy rather than a bucket listing,
        so we serve the constant view — real access control is the IAM
        policy layer."""
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        if key is not None:
            entry = self.client.get_entry(self._object_path(bucket, key))
            if entry is None or entry.get("is_directory"):
                return _err("NoSuchKey", key)
        owner = {"ID": "seaweedfs", "DisplayName": "seaweedfs"}
        return 200, to_xml(
            "AccessControlPolicy",
            {
                "Owner": owner,
                "AccessControlList": {
                    "Grant": {"Grantee": owner, "Permission": "FULL_CONTROL"}
                },
            },
        )

    def _select_object(self, bucket, key, query, body):
        """SelectObjectContent (POST /bucket/key?select&select-type=2):
        validate the request XML at the gateway so protocol errors never
        round-trip, then run the scan on the filer — next to its
        prefetching chunk stream — and relay the framed event stream."""
        if query.get("select-type") != "2":
            return _err(
                "InvalidRequest", key, "select-type=2 is required"
            )
        from ..query import select as s3select

        try:
            s3select.parse_select_request(body)
        except s3select.SelectError as e:
            return _err(e.code, key, e.message)
        path = self._object_path(bucket, key)
        entry = self.client.get_entry(path)
        if entry is None or entry.get("is_directory"):
            return _err("NoSuchKey", key)
        status, payload, err = self.client.select(path, body)
        if status != 200:
            return _err(
                err.get("error_code") or "InternalError",
                key,
                err.get("error", ""),
            )
        return 200, payload, {"Content-Type": "application/octet-stream"}

    def _get_object(self, bucket, key, headers, head=False):
        path = self._object_path(bucket, key)
        entry = self.client.get_entry(path)
        if entry is None or entry.get("is_directory"):
            return _err("NoSuchKey", key)
        size = max(
            (c["offset"] + c["size"] for c in entry.get("chunks", [])), default=0
        )
        resp_headers = {
            "Content-Type": entry.get("mime") or "application/octet-stream",
            "ETag": f'"{entry.get("extended", {}).get("md5", "")}"',
            "Last-Modified": datetime.fromtimestamp(
                entry.get("mtime", 0), tz=timezone.utc
            ).strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "Accept-Ranges": "bytes",
        }
        for k, v in entry.get("extended", {}).items():
            if k.startswith("X-Amz-Meta-"):
                resp_headers[k] = v
        if head:
            resp_headers["Content-Length-Override"] = str(size)
            return 200, b"", resp_headers
        rng = headers.get("Range", "")
        status, data, h = self.client.get_object_stream(path, rng=rng or None)
        if status not in (200, 206):
            if hasattr(data, "close"):
                data.close()
            return _err("NoSuchKey", key)
        if status == 206 and "Content-Range" in h:
            resp_headers["Content-Range"] = h["Content-Range"]
        clen = h.get("Content-Length")
        if clen is None:
            # without an upstream length a relayed body would corrupt
            # keep-alive framing; the filer always sends one, so this is
            # a broken upstream — fail loudly instead
            data.close()
            return _err("InternalError", key)
        # file-like body: the handler streams it through in pieces
        resp_headers["Content-Length-Override"] = clen
        return status, data, resp_headers

    async def _get_object_native(self, h, path, query):
        """Native-async GetObject: SigV4/SigV2 verification is pure HMAC
        (runs on the loop), bucket policy comes from the cache ONLY, and
        both the entry lookup and the body ride the asyncio pooled
        transport to the filer. Every edge falls back to the bridged
        handler for canonical error/XML bytes: sub-resources and
        presigned URLs (query params), auth failures, anonymous access,
        uncached policies, missing keys, directories."""
        from ..server.aio_transport import AStreamBody
        from ..server.aio_transport import request as arequest
        from ..server.aio_transport import stream as astream
        from ..server.http_util import NATIVE_FALLBACK, AsyncStreamBody

        if query:
            return NATIVE_FALLBACK  # ?subresource / presigned stay bridged
        headers = {k.title(): v for k, v in h.headers.items()}
        try:
            identity, err = self.iam.authenticate(
                "GET", path, query, headers, b""
            )
        except Exception:  # noqa: BLE001 — bridge renders the auth error
            return NATIVE_FALLBACK
        if err:
            return NATIVE_FALLBACK  # incl. anonymous: policy Allow is rare
        upath = urllib.parse.unquote(path)
        parts = upath.lstrip("/").split("/", 1)
        bucket = parts[0] if parts[0] else ""
        key = parts[1] if len(parts) > 1 else ""
        if not bucket or not key or bucket.startswith("."):
            return NATIVE_FALLBACK  # service/bucket ops, internal dirs
        with self._policy_lock:
            cached = self._policy_cache.get(bucket)
        if cached is None:
            return NATIVE_FALLBACK  # bridge fetches + caches the policy
        pol = cached[0]
        verdict = None
        if pol is not None:
            verdict = pe.evaluate(
                pol,
                identity.access_key if identity else "",
                pe.ACTION_NAMES.get(s3auth.ACTION_READ, "s3:*"),
                pe.arn(bucket, key),
            )
        if verdict is None:
            verdict = identity is None or identity.can_do(
                s3auth.ACTION_READ, bucket
            )
        if not verdict:
            return NATIVE_FALLBACK  # canonical AccessDenied stays bridged
        t0 = time.monotonic()
        opath = self._object_path(bucket, key)
        try:
            status, body, _ = await arequest(
                "GET", self.client._u(opath, meta="true")
            )
        except Exception:  # noqa: BLE001 — bridged client owns retries
            return NATIVE_FALLBACK
        if status != 200:
            return NATIVE_FALLBACK  # canonical NoSuchKey stays bridged
        entry = json.loads(body)
        if entry.get("is_directory"):
            return NATIVE_FALLBACK
        resp_headers = {
            "Content-Type": entry.get("mime") or "application/octet-stream",
            "ETag": f'"{entry.get("extended", {}).get("md5", "")}"',
            "Last-Modified": datetime.fromtimestamp(
                entry.get("mtime", 0), tz=timezone.utc
            ).strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "Accept-Ranges": "bytes",
        }
        for k, v in entry.get("extended", {}).items():
            if k.startswith("X-Amz-Meta-"):
                resp_headers[k] = v
        rng = headers.get("Range", "")
        try:
            status, data, rh = await astream(
                "GET", self.client._u(opath),
                headers={"Range": rng} if rng else None,
            )
        except Exception:  # noqa: BLE001
            return NATIVE_FALLBACK
        if status not in (200, 206) or not isinstance(data, AStreamBody):
            if hasattr(data, "close"):
                data.close()
            return NATIVE_FALLBACK
        if data.length is None:
            data.close()
            return NATIVE_FALLBACK  # unframed upstream: bridge fails loudly
        if status == 206 and "content-range" in rh:
            resp_headers["Content-Range"] = rh["content-range"]

        async def pieces(src):
            try:
                while True:
                    chunk = await src.read(1 << 16)
                    if not chunk:
                        break
                    yield chunk
            finally:
                src.close()
                self._req_hist.observe(
                    time.monotonic() - t0, op="object_get"
                )

        return status, AsyncStreamBody(data.length, pieces(data)), resp_headers

    def _delete_object(self, bucket, key):
        path = self._object_path(bucket, key.rstrip("/"))
        entry = self.client.get_entry(path)
        if entry is None:
            return 204, b""  # S3: deleting a missing key succeeds
        if entry.get("is_directory"):
            if key.endswith("/"):
                # explicit dir marker: remove only if empty (non-recursive)
                self.client.delete(path)
            # a bare key that happens to be an implicit directory is NOT the
            # object the client named — never recursively wipe the prefix
            return 204, b""
        self.client.delete(path)
        return 204, b""

    def _delete_multiple(self, bucket, body, can_delete=None):
        try:
            root = parse_xml(body)
        except Exception:
            return _err("MalformedXML", bucket)
        deleted, errors = [], []
        for obj in findall(root, "Object"):
            key = find_text(obj, "Key")
            if not key:
                continue
            if can_delete is not None and not can_delete(key):
                errors.append({"Key": key, "Code": "AccessDenied"})
                continue
            status, _ = self._delete_object(bucket, key)
            if status in (200, 204):
                deleted.append({"Key": key})
            else:
                errors.append({"Key": key, "Code": "InternalError"})
        return 200, to_xml(
            "DeleteResult", {"Deleted": deleted, "Error": errors}
        )

    # ---------------------------------------------------------------- tagging
    def _get_tagging(self, bucket, key):
        entry = self.client.get_entry(self._object_path(bucket, key))
        if entry is None:
            return _err("NoSuchKey", key)
        tags = [
            {"Key": k[len(TAG_PREFIX) :], "Value": v}
            for k, v in entry.get("extended", {}).items()
            if k.startswith(TAG_PREFIX)
        ]
        return 200, to_xml("Tagging", {"TagSet": {"Tag": tags}})

    def _put_tagging(self, bucket, key, body):
        path = self._object_path(bucket, key)
        entry = self.client.get_entry(path)
        if entry is None:
            return _err("NoSuchKey", key)
        try:
            root = parse_xml(body)
        except Exception:
            return _err("MalformedXML", key)
        ext = {
            k: v
            for k, v in entry.get("extended", {}).items()
            if not k.startswith(TAG_PREFIX)
        }
        for tag in findall(root, "Tag"):
            ext[TAG_PREFIX + find_text(tag, "Key")] = find_text(tag, "Value")
        entry["extended"] = ext
        self.client.create_entry(path, entry)
        return 200, b""

    def _delete_tagging(self, bucket, key):
        path = self._object_path(bucket, key)
        entry = self.client.get_entry(path)
        if entry is None:
            return _err("NoSuchKey", key)
        entry["extended"] = {
            k: v
            for k, v in entry.get("extended", {}).items()
            if not k.startswith(TAG_PREFIX)
        }
        self.client.create_entry(path, entry)
        return 204, b""

    # -------------------------------------------------------------- multipart
    def _initiate_multipart(self, bucket, key, headers):
        upload_id = uuid.uuid4().hex
        self.client.mkdir(f"{UPLOADS_DIR}/{upload_id}")
        now = int(time.time())
        self.client.create_entry(
            f"{UPLOADS_DIR}/{upload_id}/.info",
            {
                "extended": {
                    "bucket": bucket,
                    "key": key,
                    "content-type": headers.get("Content-Type", ""),
                },
                "mtime": now,
                "crtime": now,
            },
        )
        return 200, to_xml(
            "InitiateMultipartUploadResult",
            {"Bucket": bucket, "Key": key, "UploadId": upload_id},
        )

    def _upload_part(self, bucket, key, q, body, headers):
        upload_id = q["uploadId"]
        try:
            part = _parse_s3_int(q["partNumber"])
        except (KeyError, ValueError):
            return _err("InvalidArgument", key,
                        "partNumber must be an integer")
        if not 1 <= part <= 10000:
            # AWS bounds; the part file name is a 5-digit field, so name
            # order == numeric order across the whole range
            return _err("InvalidArgument", key,
                        "partNumber must be between 1 and 10000")
        if self.client.get_entry(f"{UPLOADS_DIR}/{upload_id}/.info") is None:
            return _err("NoSuchUpload", upload_id)
        if headers.get("X-Amz-Copy-Source"):
            # UploadPartCopy: the part's bytes come from an existing object,
            # not the request body (the reference routes this shape to a
            # dedicated handler — s3api_server.go:61 → CopyObjectPartHandler)
            return self._copy_part(
                upload_id,
                part,
                headers["X-Amz-Copy-Source"],
                headers.get("X-Amz-Copy-Source-Range", ""),
            )
        body, chunk_err = self._decode_chunked(headers, body, key)
        if chunk_err is not None:
            return chunk_err
        r = self.client.put_object(
            f"{UPLOADS_DIR}/{upload_id}/{part:05d}.part", body
        )
        return 200, b"", {"ETag": f'"{r.get("eTag", "")}"'}

    def _copy_part(self, upload_id, part, src, rng):
        """UploadPartCopy: server-side copy of (a range of) an existing
        object into a multipart part (s3api_object_copy_handlers.go:84
        CopyObjectPartHandler). The source streams filer→filer piecewise so
        multi-GB parts copy in bounded gateway memory."""
        sb, sk = _parse_copy_source(src)
        if not sb or not sk:
            return _err("InvalidCopySource", src)
        src_path = self._object_path(sb, sk)
        entry = self.client.get_entry(src_path)
        if entry is None or entry.get("is_directory"):
            return _err("InvalidCopySource", src)
        status, resp, h = self.client.get_object_stream(src_path, rng=rng or None)
        if status not in (200, 206):
            if hasattr(resp, "close"):
                resp.close()
            return _err("InvalidCopySource", src)
        if rng and status != 206:
            # a Range the source ignored must not silently copy everything
            resp.close()
            return _err("InvalidRange", src)
        clen = h.get("Content-Length")
        if clen is None:
            # a lengthless upstream would store a truncated/empty part and
            # CompleteMultipartUpload would then assemble silent corruption;
            # the filer always sends one, so fail loudly (same stance as
            # _get_object)
            resp.close()
            return _err("InternalError", src)
        length = int(clen)
        try:
            r = self.client.put_object_stream(
                f"{UPLOADS_DIR}/{upload_id}/{part:05d}.part", resp, length
            )
        finally:
            resp.close()
        return 200, to_xml(
            "CopyPartResult",
            {
                "LastModified": _iso(time.time()),
                "ETag": f'"{r.get("eTag", "")}"',
            },
        )

    def _complete_multipart(self, bucket, key, q, body):
        """Chunk-list concatenation, no data copy (filer_multipart.go
        CompleteMultipartUpload)."""
        upload_id = q["uploadId"]
        info = self.client.get_entry(f"{UPLOADS_DIR}/{upload_id}/.info")
        if info is None:
            return _err("NoSuchUpload", upload_id)
        try:
            root = parse_xml(body)
            part_numbers = [
                int(find_text(p, "PartNumber")) for p in findall(root, "Part")
            ]
        except Exception:
            return _err("MalformedXML", key)
        if len(set(part_numbers)) != len(part_numbers):
            # a duplicated PartNumber would assemble that part's chunks
            # twice; AWS rejects the request rather than guessing
            return _err("InvalidPart", key, "duplicate part number")
        def _part_entry(part):
            pe = self.client.get_entry(
                f"{UPLOADS_DIR}/{upload_id}/{part:05d}.part"
            )
            if pe is None:
                # uploads in flight across the 04d→05d field-width upgrade
                # stored their parts under the legacy name; completing them
                # must find (and purge) those too
                pe = self.client.get_entry(
                    f"{UPLOADS_DIR}/{upload_id}/{part:04d}.part"
                )
            return pe

        # part metadata fetches are independent filer round-trips; a
        # windowed prefetch (util/pipeline.py) overlaps them while this
        # thread assembles the chunk list strictly in part order
        chunks, md5_digests, offset = [], [], 0
        fetched = prefetch_iter(sorted(part_numbers), _part_entry, window=8)
        try:
            for part, pe in fetched:
                if pe is None:
                    return _err("InvalidPart", str(part))
                md5_digests.append(
                    bytes.fromhex(pe.get("extended", {}).get("md5", ""))
                )
                for c in sorted(pe.get("chunks", []), key=lambda c: c["offset"]):
                    c = dict(c)
                    c["offset"] = offset + c["offset"]
                    chunks.append(c)
                offset = max(
                    (c["offset"] + c["size"] for c in chunks), default=offset
                )
        finally:
            fetched.close()
        etag = hashlib.md5(b"".join(md5_digests)).hexdigest() + f"-{len(part_numbers)}"
        now = int(time.time())
        self.client.create_entry(
            self._object_path(bucket, key),
            {
                "mime": info.get("extended", {}).get("content-type", ""),
                "chunks": chunks,
                "extended": {"md5": etag},
                "mtime": now,
                "crtime": now,
            },
        )
        # parts not referenced by the Complete request would otherwise leak
        # their chunks — purge them explicitly first (legacy 04d names are
        # wanted too, so an upgraded-mid-upload part isn't double-purged)
        wanted = {f"{p:05d}.part" for p in part_numbers} | {
            f"{p:04d}.part" for p in part_numbers
        }
        stale = [
            e["name"]
            for e in self.client.list(f"{UPLOADS_DIR}/{upload_id}", limit=10001)
            if e["name"].endswith(".part") and e["name"] not in wanted
        ]
        if stale:
            # each delete purges that part's chunks on the volumes — slow,
            # independent round-trips, so run them under a bounded window
            pipe = BoundedExecutor(window=8, name="s3-purge")
            for name in stale:
                pipe.submit(
                    self.client.delete, f"{UPLOADS_DIR}/{upload_id}/{name}"
                )
            pipe.drain()
        # referenced parts' meta goes away; their chunks now belong to the
        # target entry
        self.client.delete(
            f"{UPLOADS_DIR}/{upload_id}", recursive=True, skip_chunk_purge=True
        )
        return 200, to_xml(
            "CompleteMultipartUploadResult",
            {
                "Location": f"/{bucket}/{key}",
                "Bucket": bucket,
                "Key": key,
                "ETag": f'"{etag}"',
            },
        )

    def _abort_multipart(self, bucket, key, q):
        upload_id = q["uploadId"]
        self.client.delete(f"{UPLOADS_DIR}/{upload_id}", recursive=True)
        return 204, b""

    def _list_parts(self, bucket, key, q):
        upload_id = q["uploadId"]
        if self.client.get_entry(f"{UPLOADS_DIR}/{upload_id}/.info") is None:
            return _err("NoSuchUpload", upload_id)
        parts = []
        for e in self.client.list(f"{UPLOADS_DIR}/{upload_id}", limit=10001):
            if not e["name"].endswith(".part"):
                continue
            size = max(
                (c["offset"] + c["size"] for c in e.get("chunks", [])), default=0
            )
            parts.append(
                {
                    "PartNumber": int(e["name"].split(".")[0]),
                    "LastModified": _iso(e.get("mtime", 0)),
                    "ETag": f'"{e.get("extended", {}).get("md5", "")}"',
                    "Size": size,
                }
            )
        return 200, to_xml(
            "ListPartsResult",
            {
                "Bucket": bucket,
                "Key": key,
                "UploadId": upload_id,
                "Part": parts,
            },
        )

    def _list_uploads(self, bucket):
        uploads = []
        for e in self.client.list(UPLOADS_DIR, limit=10000):
            if not e.get("is_directory"):
                continue
            info = self.client.get_entry(f"{UPLOADS_DIR}/{e['name']}/.info")
            if info and info.get("extended", {}).get("bucket") == bucket:
                uploads.append(
                    {
                        "Key": info["extended"].get("key", ""),
                        "UploadId": e["name"],
                        "Initiated": _iso(e.get("crtime", 0)),
                    }
                )
        return 200, to_xml(
            "ListMultipartUploadsResult",
            {"Bucket": bucket, "Upload": uploads},
        )

    # -------------------------------------------------------- post-policy
    def _post_policy_upload(self, bucket, headers, body):
        """Browser form upload with a signed policy
        (s3api_object_handlers_postpolicy.go:20). Auth lives inside the
        form, not the request headers."""
        try:
            values, file_bytes, file_name = pp.parse_multipart_form(
                body, headers.get("Content-Type", "")
            )
        except (ValueError, FileNotFoundError) as e:
            return _err("MalformedPOSTRequest", f"/{bucket}", str(e))
        values["bucket"] = bucket
        key = values.get("key", "")
        if not key:
            return _err("MalformedPOSTRequest", f"/{bucket}", "no key field")
        if "${filename}" in key:
            key = key.replace("${filename}", file_name)
            values["key"] = key
        if has_dot_segments(key):
            # same guard the PUT path applies in handle(): the filer will
            # refuse the write, so answer the client's 400 shape here
            # instead of wrapping the filer's
            return _err("InvalidArgument", f"/{bucket}/{key}",
                        "key must not contain '.' or '..' path segments")

        identity = None
        access_key = ""
        signed = (
            "signature" in values
            or "x-amz-signature" in values
            or values.get("policy")
        )
        if self.iam.enabled and signed:
            def secret_for(ak):
                ident = self.iam._by_key.get(ak)
                return ident.secret_key if ident else None

            if "signature" in values:  # SignV2 form
                ak = pp.verify_policy_signature_v2(values, secret_for)
            else:
                ak = pp.verify_policy_signature_v4(values, secret_for)
            if ak is None:
                return _err("SignatureDoesNotMatch", f"/{bucket}/{key}")
            identity = self.iam._by_key[ak]
            access_key = ak
        # the bucket policy governs form POSTs too: explicit Deny wins on
        # every write path, and an Allow admits principals (incl. anonymous)
        # beyond their identity grant list
        pol = self._bucket_policy(bucket)
        verdict = None
        if pol is not None:
            verdict = pe.evaluate(
                pol, access_key, "s3:PutObject", pe.arn(bucket, key)
            )
        if verdict is False:
            return _err("AccessDenied", f"/{bucket}/{key}")
        if self.iam.enabled and verdict is not True:
            if identity is None:  # unsigned form, no policy Allow
                return _err("AccessDenied", f"/{bucket}/{key}")
            if not identity.can_do(s3auth.ACTION_WRITE, bucket):
                return _err("AccessDenied", f"/{bucket}/{key}")
        if values.get("policy"):
            try:
                policy = pp.parse_post_policy(pp.decode_policy(values))
                pp.check_post_policy(values, policy)
            except ValueError as e:
                return _err(
                    "PostPolicyInvalidCondition", f"/{bucket}/{key}", str(e)
                )
            if policy.length_min >= 0 and len(file_bytes) < policy.length_min:
                return _err("EntityTooSmall", f"/{bucket}/{key}")
            if 0 <= policy.length_max < len(file_bytes):
                return _err("EntityTooLarge", f"/{bucket}/{key}")
        elif identity is not None:
            # authenticated posts must carry a policy (the signature signs it)
            return _err("MalformedPOSTRequest", f"/{bucket}/{key}", "no policy")

        ctype = values.get("content-type", "application/octet-stream")
        res = self._put_object(
            bucket, key, {"Content-Type": ctype}, file_bytes
        )
        status = res[0]
        if status not in (200, 201):
            return res
        # advertise the same ETag a later GET/HEAD will serve
        etag = (res[2].get("ETag", "") if len(res) == 3 else "").strip('"')
        etag = etag or hashlib.md5(file_bytes).hexdigest()
        redirect = values.get("success_action_redirect", "")
        if redirect:
            sep = "&" if "?" in redirect else "?"
            loc = f"{redirect}{sep}bucket={bucket}&key=" + urllib.parse.quote(
                key
            ) + f"&etag=%22{etag}%22"
            return 303, b"", {"Location": loc}
        want_status = values.get("success_action_status", "204")
        if want_status == "201":
            return 201, to_xml(
                "PostResponse",
                {
                    "Location": f"/{bucket}/{key}",
                    "Bucket": bucket,
                    "Key": key,
                    "ETag": f'"{etag}"',
                },
            )
        return (200, b"") if want_status == "200" else (204, b"")

    # -------------------------------------------------------- bucket policy
    # Stored under /etc (like the reference's s3 config subtree), NOT under
    # /buckets — a policy document must never be addressable as an object,
    # or a plain Write grant could rewrite any bucket's policy.
    POLICIES_DIR = "/etc/s3/policies"

    def _bucket_policy(self, bucket):
        """Cached parse of the bucket's policy document (None = no policy)."""
        with self._policy_lock:
            cached = self._policy_cache.get(bucket)
            gen = self._policy_gen.get(bucket, 0)
        if cached is not None:
            return cached[0]
        status, data, _ = self.client.get_object(
            f"{self.POLICIES_DIR}/{bucket}"
        )
        pol = None
        if status == 200 and data:
            try:
                pol = pe.parse_bucket_policy(data)
            except (ValueError, KeyError):
                pol = None
        with self._policy_lock:
            if self._policy_gen.get(bucket, 0) != gen:
                return pol  # invalidated mid-read: serve but don't cache
            while len(self._policy_cache) >= 1024:  # bound negative entries
                self._policy_cache.pop(next(iter(self._policy_cache)))
            self._policy_cache[bucket] = (pol,)
        return pol

    def _put_bucket_policy(self, bucket, body):
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        try:
            pe.parse_bucket_policy(body)
        except (ValueError, KeyError) as e:
            return _err("MalformedPolicy", bucket, str(e))
        self.client.put_object(f"{self.POLICIES_DIR}/{bucket}", body)
        with self._policy_lock:
            self._policy_gen[bucket] = self._policy_gen.get(bucket, 0) + 1
            self._policy_cache.pop(bucket, None)
        return 204, b""

    def _get_bucket_policy(self, bucket):
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        status, data, _ = self.client.get_object(
            f"{self.POLICIES_DIR}/{bucket}"
        )
        if status != 200 or not data:
            return _err("NoSuchBucketPolicy", bucket)
        return 200, data, {"Content-Type": "application/json"}

    def _delete_bucket_policy(self, bucket):
        if not self._bucket_exists(bucket):
            return _err("NoSuchBucket", bucket)
        self.client.delete(f"{self.POLICIES_DIR}/{bucket}")
        with self._policy_lock:
            self._policy_gen[bucket] = self._policy_gen.get(bucket, 0) + 1
            self._policy_cache.pop(bucket, None)
        return 204, b""

    # ------------------------------------------------------------------ router
    def handle(self, method, raw_path, query, headers, body):
        path_probe = urllib.parse.unquote(raw_path).lstrip("/")
        if (
            method == "POST"
            and path_probe
            and not path_probe.startswith(".")
            and "/" not in path_probe.rstrip("/")
            and headers.get("Content-Type", "").startswith(
                "multipart/form-data"
            )
        ):
            # bucket-level form POST: auth is in the form, not the headers
            # (s3api_server.go:101 routes these before the auth wrapper)
            return self._post_policy_upload(
                path_probe.rstrip("/"), headers, body
            )
        identity, err = self.iam.authenticate(
            method, raw_path, query, headers, body
        )
        # an unsigned request is not an auth *failure* — it falls through as
        # anonymous so a bucket policy with Principal "*" can admit it
        # (public buckets). Bad signatures still hard-fail.
        anonymous = (
            err == "AccessDenied"
            and not headers.get("Authorization")
            and "X-Amz-Algorithm" not in query
            and "Signature" not in query
        )
        if err and not anonymous:
            return _err(err, raw_path)
        path = urllib.parse.unquote(raw_path)
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0] if parts[0] else ""
        key = parts[1] if len(parts) > 1 else ""
        if bucket.startswith("."):
            # dot-prefixed names would collide with the gateway's internal
            # dirs under /buckets (.uploads); S3 names start alphanumeric
            return _err("InvalidBucketName", path)
        if method in ("PUT", "POST") and has_dot_segments(key):
            # keys are filer paths here: the filer refuses literal "."/".."
            # segments on writes (unrepresentable through the FUSE mount),
            # so answer the client's error shape instead of wrapping the
            # filer's 400. GET/DELETE stay literal — pre-existing artifacts
            # remain readable and deletable so buckets can be emptied.
            return _err("InvalidArgument", path,
                        "key must not contain '.' or '..' path segments")

        def allowed(action, s3_action="", obj_key=None):
            # resource policy first (explicit Deny wins, Allow grants even
            # beyond the identity grant list), then identity grants.
            # s3_action picks the exact policy action name when the coarse
            # gate is ambiguous (s3:DeleteObject vs s3:PutObject, …).
            if bucket:
                pol = self._bucket_policy(bucket)
                if pol is not None:
                    who = identity.access_key if identity else ""
                    name = s3_action or pe.ACTION_NAMES.get(action, "s3:*")
                    k = key if obj_key is None else obj_key
                    res = pe.arn(bucket, k) if k else pe.arn(bucket)
                    verdict = pe.evaluate(pol, who, name, res)
                    if verdict is not None:
                        return verdict
            if anonymous:
                return False  # only an explicit policy Allow admits anonymous
            return identity is None or identity.can_do(action, bucket)

        src_hdr = headers.get("X-Amz-Copy-Source", "")
        if src_hdr and method == "PUT":
            # copy sources are an independent READ of another resource: the
            # destination-bucket write grant must not leak other tenants'
            # bytes (or gateway-internal dirs like .uploads) through a copy
            sb, sk = _parse_copy_source(src_hdr)
            if not sb or not sk or sb.startswith("."):
                return _err("InvalidCopySource", path)
            src_pol = self._bucket_policy(sb)
            verdict = None
            if src_pol is not None:
                verdict = pe.evaluate(
                    src_pol,
                    identity.access_key if identity else "",
                    "s3:GetObject",
                    pe.arn(sb, sk),
                )
            if verdict is None:
                verdict = (
                    not anonymous
                    and (
                        identity is None
                        or identity.can_do(s3auth.ACTION_READ, sb)
                    )
                )
            if not verdict:
                return _err("AccessDenied", path)

        # ?policy subresource (PutBucketPolicy / GetBucketPolicy / Delete)
        if bucket and not key and "policy" in query:
            if self.iam.enabled and (
                identity is None
                or not identity.can_do(s3auth.ACTION_ADMIN, bucket)
            ):
                return _err("AccessDenied", path)
            if method == "PUT":
                return self._put_bucket_policy(bucket, body)
            if method == "GET":
                return self._get_bucket_policy(bucket)
            if method == "DELETE":
                return self._delete_bucket_policy(bucket)

        if not bucket:
            if method == "GET":
                if not allowed(s3auth.ACTION_LIST):
                    return _err("AccessDenied", path)
                return self._list_buckets(identity)
            return _err("MethodNotAllowed", path)

        if not key:
            if method == "PUT":
                if "acl" in query:
                    if not allowed(s3auth.ACTION_ADMIN):
                        return _err("AccessDenied", path)
                    if not self._bucket_exists(bucket):
                        return _err("NoSuchBucket", bucket)
                    return 200, b""  # accepted no-op, like GET ?acl's canned view
                if not allowed(s3auth.ACTION_ADMIN, "s3:CreateBucket"):
                    return _err("AccessDenied", path)
                return self._put_bucket(bucket)
            if method == "HEAD":
                if not allowed(s3auth.ACTION_READ, "s3:ListBucket"):
                    return _err("AccessDenied", path)
                return self._head_bucket(bucket)
            if method == "DELETE":
                if not allowed(s3auth.ACTION_ADMIN, "s3:DeleteBucket"):
                    return _err("AccessDenied", path)
                return self._delete_bucket(bucket)
            if method == "POST" and "delete" in query:
                # per-key policy evaluation — an object-scoped Deny must
                # cover the batch path exactly like single DELETEs
                return self._delete_multiple(
                    bucket,
                    body,
                    can_delete=lambda k: allowed(
                        s3auth.ACTION_WRITE, "s3:DeleteObject", obj_key=k
                    ),
                )
            if method == "GET":
                if not allowed(s3auth.ACTION_LIST):
                    return _err("AccessDenied", path)
                if "acl" in query:
                    return self._get_acl(bucket)
                if "uploads" in query:
                    return self._list_uploads(bucket)
                if "location" in query:
                    return 200, to_xml("LocationConstraint", "")
                return self._list_objects(
                    bucket, query, v2=query.get("list-type") == "2"
                )
            return _err("MethodNotAllowed", path)

        # object-level
        if "tagging" in query:
            tag_action = {
                "GET": "s3:GetObjectTagging",
                "PUT": "s3:PutObjectTagging",
                "DELETE": "s3:DeleteObjectTagging",
            }.get(method, "s3:PutObjectTagging")
            if not allowed(s3auth.ACTION_TAGGING, tag_action):
                return _err("AccessDenied", path)
            if method == "GET":
                return self._get_tagging(bucket, key)
            if method == "PUT":
                return self._put_tagging(bucket, key, body)
            if method == "DELETE":
                return self._delete_tagging(bucket, key)
        if method == "POST" and "select" in query:
            # SelectObjectContent reads object content: gate exactly like
            # a GET of the same key
            if not allowed(s3auth.ACTION_READ, "s3:GetObject"):
                return _err("AccessDenied", path)
            return self._select_object(bucket, key, query, body)
        if method == "POST" and "uploads" in query:
            if not allowed(s3auth.ACTION_WRITE):
                return _err("AccessDenied", path)
            return self._initiate_multipart(bucket, key, headers)
        if method == "POST" and "uploadId" in query:
            if not allowed(s3auth.ACTION_WRITE):
                return _err("AccessDenied", path)
            return self._complete_multipart(bucket, key, query, body)
        if method == "PUT" and "uploadId" in query:
            if not allowed(s3auth.ACTION_WRITE):
                return _err("AccessDenied", path)
            return self._upload_part(bucket, key, query, body, headers)
        if method == "DELETE" and "uploadId" in query:
            if not allowed(s3auth.ACTION_WRITE, "s3:AbortMultipartUpload"):
                return _err("AccessDenied", path)
            return self._abort_multipart(bucket, key, query)
        if method == "GET" and "uploadId" in query:
            if not allowed(s3auth.ACTION_READ, "s3:ListMultipartUploadParts"):
                return _err("AccessDenied", path)
            return self._list_parts(bucket, key, query)
        if "acl" in query:
            # GET serves the canned owner view; PUT is an accepted no-op —
            # either falling through would corrupt the object (PUT would
            # store the ACL XML as the object body)
            if method == "GET":
                if not allowed(s3auth.ACTION_READ):
                    return _err("AccessDenied", path)
                return self._get_acl(bucket, key)
            if method == "PUT":
                if not allowed(s3auth.ACTION_WRITE):
                    return _err("AccessDenied", path)
                entry = self.client.get_entry(self._object_path(bucket, key))
                if entry is None or entry.get("is_directory"):
                    return _err("NoSuchKey", key)
                return 200, b""
            return _err("MethodNotAllowed", path)
        if method == "PUT":
            if not allowed(s3auth.ACTION_WRITE):
                return _err("AccessDenied", path)
            return self._put_object(bucket, key, headers, body)
        if method in ("GET", "HEAD"):
            if not allowed(s3auth.ACTION_READ):
                return _err("AccessDenied", path)
            return self._get_object(bucket, key, headers, head=(method == "HEAD"))
        if method == "DELETE":
            if not allowed(s3auth.ACTION_WRITE, "s3:DeleteObject"):
                return _err("AccessDenied", path)
            return self._delete_object(bucket, key)
        return _err("MethodNotAllowed", path)

    # --------------------------------------------------------------- lifecycle
    def start(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True  # keep-alive + Nagle = ~40ms RTTs
            trace_service = "s3"
            # hot GetObject served natively on the loop (aio mode); every
            # edge falls back to the bridged _go path for canonical bytes
            native_routes = [("GET", "/", api._get_object_native)]

            def log_message(self, fmt, *args):
                pass

            def _go(self, method):
                parsed = urllib.parse.urlparse(self.path)
                query = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(
                        parsed.query, keep_blank_values=True
                    ).items()
                }
                length = parse_content_length(self.headers)
                if length < 0:
                    # framing is unknowable → 400 and drop the connection
                    self.close_connection = True
                    data = error_xml(
                        "IncompleteBody", "bad Content-Length", parsed.path
                    )
                    self.send_response(400)
                    self.send_header("Content-Type", "application/xml")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                headers = {k.title(): v for k, v in self.headers.items()}
                # stream-eligible object PUT: auth never needs the bytes
                # (unsigned/absent payload hash) and no sub-resource is
                # addressed, so the body can flow straight to the filer
                sha = headers.get("X-Amz-Content-Sha256", "")
                reader = None
                if (
                    method == "PUT"
                    and length > 0
                    and sha in ("", s3auth.UNSIGNED_PAYLOAD)
                    and not query
                    and "X-Amz-Copy-Source" not in headers
                    # a real /bucket/key — '/bucket' and '/bucket/' are
                    # bucket ops whose handlers never consume a body
                    and parsed.path.rstrip("/").count("/") >= 2
                ):
                    reader = CountedReader(self.rfile, length)
                    body = (reader, length)
                else:
                    body = self.rfile.read(length) if length else b""
                # span + latency classification: bucket vs object op keeps
                # the label space bounded (full path rides the span tag)
                p = parsed.path.strip("/")
                kind = "object" if "/" in p else ("bucket" if p else "service")
                with _trace.start_span(
                    f"{method} s3:{kind}",
                    service="s3",
                    parent_header=headers.get(_trace.TRACE_HEADER),
                    path=parsed.path,
                ) as span, api._req_hist.time(op=f"{kind}_{method.lower()}"):
                    try:
                        result = api.handle(
                            method, parsed.path, query, headers, body
                        )
                    except Exception as e:  # noqa: BLE001
                        result = 500, error_xml(
                            "InternalError", str(e), parsed.path
                        )
                    if reader is not None and reader.left > 0:
                        # refused before the body was consumed: bounded,
                        # timeout-guarded drain (http_util.drain_refused_body)
                        drain_refused_body(self, reader)
                    if len(result) == 2:
                        status, payload = result
                        extra = {}
                    else:
                        status, payload, extra = result
                    if span is not None:
                        span.tags["status"] = status
                        if status >= 500:
                            span.status = "error"
                        extra.setdefault(_trace.TRACE_ID_HEADER, span.trace_id)
                    self.send_response(status)
                    streaming = hasattr(payload, "read")
                    clen = extra.pop("Content-Length-Override", None)
                    ctype = extra.pop(
                        "Content-Type",
                        "application/xml"
                        if payload
                        else "application/octet-stream",
                    )
                    self.send_header("Content-Type", ctype)
                    if streaming:
                        self.send_header("Content-Length", clen)  # always set
                    else:
                        self.send_header(
                            "Content-Length", clen or str(len(payload))
                        )
                    for k, v in extra.items():
                        self.send_header(k, v)
                    self.end_headers()
                    if streaming:
                        if method == "HEAD":
                            payload.close()
                        else:
                            relay_stream(self, payload, int(clen))
                    elif method != "HEAD" and payload:
                        self.wfile.write(payload)

            def do_GET(self):
                self._go("GET")

            def do_PUT(self):
                self._go("PUT")

            def do_POST(self):
                self._go("POST")

            def do_DELETE(self):
                self._go("DELETE")

            def do_HEAD(self):
                self._go("HEAD")

        from ..security.tls import optional_server_context

        ctx = optional_server_context(*self._tls)
        self._srv = start_server(Handler, self.host, self.port, ssl_context=ctx)
        return self

    def stop(self):
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"


def _err(code: str, resource: str, message: str = ""):
    status = _ERR_STATUS.get(code, 400)
    return status, error_xml(code, message or code, resource)
