"""AWS signature authentication for the S3 gateway.

Implements the subset the reference ships (`weed/s3api/auth_credentials.go:124`,
`auth_signature_v4.go`, `auth_signature_v2.go`, `chunked_reader_v4.go`):

- Signature V4: `Authorization` header, presigned query (`X-Amz-Signature`),
  and streaming uploads (`STREAMING-AWS4-HMAC-SHA256-PAYLOAD`) whose body is
  the aws-chunked framing with a per-chunk signature chain.
- Signature V2: `Authorization: AWS key:sig` and presigned (`?Signature=`).
- Identities with per-action grants: Admin, Read, Write, List, Tagging —
  optionally scoped `Action:bucket` (`auth_credentials.go` Identity.canDo).

When no identities are configured every request is allowed (the reference's
"not enabled" mode).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time as _time
import urllib.parse
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Optional

from ..util.parsers import parse_ascii_uint

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"

# s3err codes the handlers map to HTTP statuses
ERR_NONE = None
ERR_ACCESS_DENIED = "AccessDenied"
ERR_INVALID_ACCESS_KEY = "InvalidAccessKeyId"
ERR_SIGNATURE_MISMATCH = "SignatureDoesNotMatch"
ERR_MISSING_FIELDS = "MissingFields"
ERR_EXPIRED_REQUEST = "ExpiredPresignRequest"
# malformed presign query values (non-numeric X-Amz-Expires etc.) are the
# client's error: AWS answers 400 AuthorizationQueryParametersError, and
# anything else here either coerces ('+5' parsed as 5) or turns into a 500
ERR_MALFORMED_QUERY = "AuthorizationQueryParametersError"
# the reference's ErrRequestNotReadyYet serializes as code "AccessDenied"
# with 403 (s3api_errors.go:317-321) — a URL dated in the future is not
# "expired", it has not begun its validity window
ERR_REQUEST_NOT_READY = "AccessDenied"


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=list)

    def can_do(self, action: str, bucket: str = "") -> bool:
        if ACTION_ADMIN in self.actions:
            return True
        if action in self.actions:
            return True
        return bucket and f"{action}:{bucket}" in self.actions


class IAM:
    """Identity registry + request authentication (auth_credentials.go)."""

    def __init__(self, identities: Optional[list[Identity]] = None):
        self.identities = identities or []
        self._by_key = {i.access_key: i for i in self.identities}

    @classmethod
    def from_config(cls, conf: dict) -> "IAM":
        """Accepts the reference's s3.json shape: {"identities": [{"name":...,
        "credentials": [{"accessKey":..., "secretKey":...}], "actions":[...]}]}"""
        ids = []
        for d in conf.get("identities", []):
            for cred in d.get("credentials", [{}]):
                ids.append(
                    Identity(
                        name=d.get("name", ""),
                        access_key=cred.get("accessKey", ""),
                        secret_key=cred.get("secretKey", ""),
                        actions=list(d.get("actions", [])),
                    )
                )
        return cls(ids)

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    # -- entry point ----------------------------------------------------------
    def authenticate(
        self,
        method: str,
        path: str,
        query: dict[str, str],
        headers: dict[str, str],
        body: bytes,
    ) -> tuple[Optional[Identity], Optional[str]]:
        """Returns (identity, error_code). identity None + error None means
        anonymous allowed (auth disabled)."""
        if not self.enabled:
            return None, ERR_NONE
        auth = headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256"):
            return self._check_v4_header(method, path, query, headers, body, auth)
        if auth.startswith("AWS "):
            return self._check_v2_header(method, path, query, headers, auth)
        if query.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._check_v4_presigned(method, path, query, headers)
        if "Signature" in query and "AWSAccessKeyId" in query:
            return self._check_v2_presigned(method, path, query)
        return None, ERR_ACCESS_DENIED

    # -- v4 -------------------------------------------------------------------
    @staticmethod
    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    @classmethod
    def signing_key(cls, secret: str, date: str, region: str, service: str) -> bytes:
        k = cls._hmac(("AWS4" + secret).encode(), date)
        k = cls._hmac(k, region)
        k = cls._hmac(k, service)
        return cls._hmac(k, "aws4_request")

    @staticmethod
    def _canonical_uri(path: str) -> str:
        # the wire-format (already percent-encoded) path is the canonical URI
        # for S3; re-encoding would break real clients (boto signs the
        # encoded form once)
        return path or "/"

    @staticmethod
    def _canonical_query(query: dict[str, str], skip: tuple = ()) -> str:
        parts = []
        for k in sorted(query):
            if k in skip:
                continue
            parts.append(
                urllib.parse.quote(k, safe="~-._")
                + "="
                + urllib.parse.quote(query[k], safe="~-._")
            )
        return "&".join(parts)

    @staticmethod
    def _canonical_headers(
        headers: dict[str, str], signed: list[str]
    ) -> str:
        low = {k.lower(): v for k, v in headers.items()}
        return "".join(
            f"{h}:{' '.join(low.get(h, '').split())}\n" for h in signed
        )

    def _v4_signature(
        self,
        secret: str,
        method: str,
        path: str,
        query: dict[str, str],
        headers: dict[str, str],
        signed_headers: list[str],
        payload_hash: str,
        amz_date: str,
        scope: str,
        skip_q: tuple = (),
    ) -> str:
        canonical = "\n".join(
            [
                method,
                self._canonical_uri(path),
                self._canonical_query(query, skip=skip_q),
                self._canonical_headers(headers, signed_headers),
                ";".join(signed_headers),
                payload_hash,
            ]
        )
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )
        date, region, service, _ = scope.split("/")
        key = self.signing_key(secret, date, region, service)
        return hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()

    def _check_v4_header(self, method, path, query, headers, body, auth):
        try:
            # AWS4-HMAC-SHA256 Credential=ak/scope, SignedHeaders=a;b, Signature=x
            fields = dict(
                f.strip().split("=", 1)
                for f in auth[len("AWS4-HMAC-SHA256") :].split(",")
            )
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            given_sig = fields["Signature"]
            access_key, scope = cred.split("/", 1)
        except (KeyError, ValueError):
            return None, ERR_MISSING_FIELDS
        ident = self._by_key.get(access_key)
        if ident is None:
            return None, ERR_INVALID_ACCESS_KEY
        payload_hash = headers.get("X-Amz-Content-Sha256", "")
        if payload_hash == STREAMING_PAYLOAD:
            pass  # seed check only; chunks verified by ChunkedDecoder
        elif payload_hash in ("", UNSIGNED_PAYLOAD):
            payload_hash = payload_hash or UNSIGNED_PAYLOAD
        else:
            if hashlib.sha256(body).hexdigest() != payload_hash:
                return None, ERR_SIGNATURE_MISMATCH
        amz_date = headers.get("X-Amz-Date", "") or headers.get("Date", "")
        sig = self._v4_signature(
            ident.secret_key,
            method,
            path,
            query,
            headers,
            signed_headers,
            payload_hash,
            amz_date,
            scope,
        )
        if not hmac.compare_digest(sig, given_sig):
            return None, ERR_SIGNATURE_MISMATCH
        return ident, ERR_NONE

    def _check_v4_presigned(self, method, path, query, headers):
        try:
            access_key, scope = query["X-Amz-Credential"].split("/", 1)
            signed_headers = query["X-Amz-SignedHeaders"].split(";")
            given_sig = query["X-Amz-Signature"]
            amz_date = query["X-Amz-Date"]
        except KeyError:
            return None, ERR_MISSING_FIELDS
        ident = self._by_key.get(access_key)
        if ident is None:
            return None, ERR_INVALID_ACCESS_KEY
        try:
            signed_at = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=timezone.utc
            )
        except ValueError:
            return None, ERR_MISSING_FIELDS
        try:
            # strict ascii-digit parse: plain int() would accept '+5' and
            # ' 5 ' (values AWS rejects) and still 500 on garbage
            expires = parse_ascii_uint(query.get("X-Amz-Expires", "604800"))
        except ValueError:
            return None, ERR_MALFORMED_QUERY
        if _time.time() > signed_at.timestamp() + expires:
            return None, ERR_EXPIRED_REQUEST
        # a URL "signed" in the future defeats X-Amz-Expires (it would stay
        # valid for future+expires); the reference allows only 15 minutes of
        # clock skew ahead (auth_signature_v4.go:361-364)
        if signed_at.timestamp() > _time.time() + 15 * 60:
            return None, ERR_REQUEST_NOT_READY
        sig = self._v4_signature(
            ident.secret_key,
            method,
            path,
            query,
            headers,
            signed_headers,
            UNSIGNED_PAYLOAD,
            amz_date,
            scope,
            skip_q=("X-Amz-Signature",),
        )
        if not hmac.compare_digest(sig, given_sig):
            return None, ERR_SIGNATURE_MISMATCH
        return ident, ERR_NONE

    def streaming_context(self, headers: dict) -> Optional["StreamingContext"]:
        """Chunk-verification chain for a just-authenticated streaming upload
        (None when auth is disabled or the request wasn't V4-signed)."""
        if not self.enabled:
            return None
        auth = headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return None
        try:
            fields = dict(
                f.strip().split("=", 1)
                for f in auth[len("AWS4-HMAC-SHA256") :].split(",")
            )
            access_key, scope = fields["Credential"].split("/", 1)
        except (KeyError, ValueError):
            return None
        ident = self._by_key.get(access_key)
        if ident is None:
            return None
        return StreamingContext(
            ident.secret_key,
            scope,
            headers.get("X-Amz-Date", ""),
            fields["Signature"],
        )

    # -- v2 (legacy) ----------------------------------------------------------
    def _v2_string_to_sign(self, method, path, query, headers) -> str:
        sub_resources = sorted(
            k
            for k in query
            if k
            in (
                "acl", "delete", "lifecycle", "location", "logging",
                "notification", "partNumber", "policy", "requestPayment",
                "tagging", "torrent", "uploadId", "uploads", "versionId",
                "versioning", "versions", "website",
            )
        )
        canon_resource = path
        if sub_resources:
            canon_resource += "?" + "&".join(
                k if not query[k] else f"{k}={query[k]}" for k in sub_resources
            )
        amz = {
            k.lower(): v for k, v in headers.items() if k.lower().startswith("x-amz-")
        }
        amz_lines = "".join(f"{k}:{amz[k]}\n" for k in sorted(amz))
        return "\n".join(
            [
                method,
                headers.get("Content-Md5", ""),
                headers.get("Content-Type", ""),
                headers.get("Date", "") if "x-amz-date" not in amz else "",
            ]
        ) + "\n" + amz_lines + canon_resource

    def _check_v2_header(self, method, path, query, headers, auth):
        try:
            access_key, given = auth[4:].split(":", 1)
        except ValueError:
            return None, ERR_MISSING_FIELDS
        ident = self._by_key.get(access_key)
        if ident is None:
            return None, ERR_INVALID_ACCESS_KEY
        sts = self._v2_string_to_sign(method, path, query, headers)
        sig = base64.b64encode(
            hmac.new(ident.secret_key.encode(), sts.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(sig, given):
            return None, ERR_SIGNATURE_MISMATCH
        return ident, ERR_NONE

    def _check_v2_presigned(self, method, path, query):
        ident = self._by_key.get(query["AWSAccessKeyId"])
        if ident is None:
            return None, ERR_INVALID_ACCESS_KEY
        try:
            # strict: a V2 presign whose Expires is not a plain epoch
            # integer is denied (AWS: 403 "Invalid date format"), not
            # coerced and not a 500
            expires_at = parse_ascii_uint(query.get("Expires", "0"))
        except ValueError:
            return None, ERR_ACCESS_DENIED
        if _time.time() > expires_at:
            return None, ERR_EXPIRED_REQUEST
        sts = "\n".join(
            [method, "", "", query.get("Expires", "")]
        ) + "\n" + path
        sig = base64.b64encode(
            hmac.new(ident.secret_key.encode(), sts.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(sig, query["Signature"]):
            return None, ERR_SIGNATURE_MISMATCH
        return ident, ERR_NONE


class ChunkSignatureError(Exception):
    pass


def decode_aws_chunked(
    body: bytes, verify: Optional["StreamingContext"] = None
) -> bytes:
    """Decode the aws-chunked framing of STREAMING-AWS4-HMAC-SHA256-PAYLOAD
    uploads (`chunked_reader_v4.go`): repeated
    `hex-size;chunk-signature=<sig>\\r\\n<data>\\r\\n`, last chunk size 0.
    With a `StreamingContext` each chunk signature is checked against the V4
    chain seeded by the header signature; a mismatch raises
    ChunkSignatureError."""
    out = bytearray()
    pos = 0
    while pos < len(body):
        nl = body.index(b"\r\n", pos)
        header = body[pos:nl].decode()
        size_str, _, sig_part = header.partition(";")
        size = int(size_str, 16)
        if size < 0:
            raise ValueError(f"negative chunk size {size_str!r}")
        pos = nl + 2
        data = body[pos : pos + size]
        if verify is not None:
            given = sig_part.partition("=")[2]
            want = verify.next_chunk_signature(data)
            # compare as bytes: compare_digest raises TypeError on non-ASCII
            # str input, which would turn a garbage signature into a 500
            if not hmac.compare_digest(given.encode(), want.encode()):
                raise ChunkSignatureError(f"chunk at {pos} signature mismatch")
        if size == 0:
            break
        out += data
        pos = pos + size + 2  # trailing \r\n
    return bytes(out)


_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class StreamingContext:
    """Per-request chunk-signature chain for streaming SigV4 uploads.

    chunk_sts = 'AWS4-HMAC-SHA256-PAYLOAD' \\n amz_date \\n scope \\n
                prev_signature \\n sha256('') \\n sha256(chunk_data)
    (AWS SigV4 streaming spec; `chunked_reader_v4.go` buildChunkStringToSign)
    """

    def __init__(self, secret: str, scope: str, amz_date: str, seed_sig: str):
        date, region, service, _ = scope.split("/")
        self.key = IAM.signing_key(secret, date, region, service)
        self.scope = scope
        self.amz_date = amz_date
        self.prev = seed_sig

    def next_chunk_signature(self, data: bytes) -> str:
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD",
                self.amz_date,
                self.scope,
                self.prev,
                _EMPTY_SHA256,
                hashlib.sha256(data).hexdigest(),
            ]
        )
        self.prev = hmac.new(self.key, sts.encode(), hashlib.sha256).hexdigest()
        return self.prev
