"""Bucket policy documents — AWS-style IAM policy evaluation for S3.

Round-1 VERDICT missing #8: beyond the per-identity grant list
(`auth_credentials.go` Identity.canDo, implemented in auth.py), the S3
surface needs resource policies: JSON documents attached to a bucket whose
statements Allow/Deny principals specific s3:* actions on resource ARNs.

Evaluation follows AWS semantics: an explicit Deny in any matching
statement wins; otherwise an Allow grants access (even to identities whose
grant list alone wouldn't); otherwise the decision falls through to the
identity grant list.

Shape (the s3:* subset the reference's ecosystem uses):
    {"Version": "2012-10-17",
     "Statement": [{"Effect": "Allow",
                    "Principal": {"AWS": ["*"]},
                    "Action": ["s3:GetObject"],
                    "Resource": "arn:aws:s3:::bucket/*"}]}
"""

from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Statement:
    effect: str  # "Allow" | "Deny"
    principals: list[str] = field(default_factory=list)  # "*" or access keys
    actions: list[str] = field(default_factory=list)  # s3:GetObject, s3:*
    resources: list[str] = field(default_factory=list)  # arn:aws:s3:::b/k


@dataclass
class BucketPolicy:
    statements: list[Statement] = field(default_factory=list)


def _as_list(v) -> list[str]:
    if v is None:
        return []
    if isinstance(v, str):
        return [v]
    return [str(x) for x in v]


def parse_bucket_policy(doc: str | bytes) -> BucketPolicy:
    d = json.loads(doc)
    out = BucketPolicy()
    for s in d.get("Statement", []):
        effect = s.get("Effect", "")
        if effect not in ("Allow", "Deny"):
            raise ValueError(f"bad Effect {effect!r}")
        principal = s.get("Principal", "*")
        if isinstance(principal, dict):
            principals = _as_list(principal.get("AWS", []))
        else:
            principals = _as_list(principal)
        actions = _as_list(s.get("Action"))
        resources = _as_list(s.get("Resource"))
        if not actions or not resources:
            raise ValueError("statement needs Action and Resource")
        for a in actions:
            if not (a == "*" or a.startswith("s3:")):
                raise ValueError(f"unsupported action {a!r}")
        out.statements.append(
            Statement(effect, principals, actions, resources)
        )
    return out


def _match_principal(principals: list[str], who: str) -> bool:
    for p in principals:
        if p == "*" or p == who:
            return True
        # arn:aws:iam::123:user/name style: match the trailing name — but
        # ONLY for actual IAM ARNs, and never for the anonymous identity
        # (who == ""): a bare name containing '/' must not alias into an
        # ARN match, and 'arn:...:user/' must not grant anonymous (ADVICE r2)
        if (
            who != ""
            and p.startswith("arn:aws:iam::")
            and "/" in p
            and p.rsplit("/", 1)[-1] == who
        ):
            return True
    return False


def _match_pattern(patterns: list[str], value: str) -> bool:
    return any(fnmatch.fnmatchcase(value, p) for p in patterns)


def evaluate(
    policy: BucketPolicy, who: str, action: str, resource: str
) -> Optional[bool]:
    """True = Allow, False = explicit Deny, None = no statement matched
    (fall through to the identity grant list). `who` is the access key or
    identity name ("" = anonymous, matched only by "*"); `action` is an
    s3:* name; `resource` is arn:aws:s3:::bucket[/key]."""
    allowed: Optional[bool] = None
    for s in policy.statements:
        if not _match_principal(s.principals, who):
            continue
        if not any(
            p == "*" or fnmatch.fnmatchcase(action, p) for p in s.actions
        ):
            continue
        if not _match_pattern(s.resources, resource):
            continue
        if s.effect == "Deny":
            return False  # explicit deny wins immediately
        allowed = True
    return allowed


# map of this server's coarse action gates → the s3:* names checked against
# bucket policies (object-level vs bucket-level chosen by the caller)
ACTION_NAMES = {
    "Read": "s3:GetObject",
    "Write": "s3:PutObject",
    "List": "s3:ListBucket",
    "Tagging": "s3:PutObjectTagging",
    "Admin": "s3:*",
}


def arn(bucket: str, key: str = "") -> str:
    return f"arn:aws:s3:::{bucket}" + (f"/{key}" if key else "")
