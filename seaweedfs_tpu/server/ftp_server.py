"""FTP gateway over the filer.

The reference shipped only an unfinished 81-line driver shell
(`weed/ftpd/ftp_server.go` — its AuthUser returns a nil driver, so it
never served a file). This is the finished equivalent: an RFC 959 server
(passive mode only, like the reference's intended setup) whose filesystem
is the filer, in the same role the WebDAV gateway plays.

Supported verbs: USER/PASS, SYST, FEAT, TYPE, PWD, CWD, CDUP, PASV, EPSV,
LIST, NLST, RETR, STOR, APPE, DELE, MKD, RMD, SIZE, MDTM, RNFR/RNTO,
NOOP, QUIT.
"""

from __future__ import annotations

import shutil
import socket
import threading
import time
from typing import Optional

from ..filer.client import FilerClient
from ..util import glog


def _join(cwd: str, arg: str) -> str:
    """Resolve an FTP path argument against the cwd, normalizing .. / ."""
    path = arg if arg.startswith("/") else f"{cwd.rstrip('/')}/{arg}"
    parts: list[str] = []
    for p in path.split("/"):
        if p in ("", "."):
            continue
        if p == "..":
            if parts:
                parts.pop()
            continue
        parts.append(p)
    return "/" + "/".join(parts)


# RFC 959 lines are short; 8KB leaves room for deep paths while bounding
# what a hostile newline-free stream can make the command reader buffer
_MAX_CMD_LINE = 8192


class _Session(threading.Thread):
    def __init__(self, srv: "FtpServer", conn: socket.socket, addr):
        super().__init__(daemon=True)
        self.srv = srv
        self.conn = conn
        self.addr = addr
        self.cwd = "/"  # virtual path; mapped under srv.root for the filer
        self.authed_user: Optional[str] = None
        self.pending_user = ""
        self.rename_from: Optional[str] = None
        self.type = "I"
        self._pasv: Optional[socket.socket] = None
        self._rfile = conn.makefile("rb")

    # -- plumbing ------------------------------------------------------------
    def send(self, code: int, text: str) -> None:
        self.conn.sendall(f"{code} {text}\r\n".encode())

    def send_multi(self, code: int, lines: list[str]) -> None:
        out = "".join(f"{code}-{ln}\r\n" for ln in lines[:-1])
        out += f"{code} {lines[-1]}\r\n"
        self.conn.sendall(out.encode())

    def _open_pasv(self) -> socket.socket:
        if self._pasv is not None:
            self._pasv.close()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind((self.srv.host, 0))
        s.listen(1)
        s.settimeout(30)
        self._pasv = s
        return s

    def _data_conn(self) -> Optional[socket.socket]:
        if self._pasv is None:
            self.send(425, "Use PASV first.")
            return None
        try:
            conn, _ = self._pasv.accept()
            return conn
        except TimeoutError:
            self.send(425, "Data connection timed out.")
            return None
        finally:
            self._pasv.close()
            self._pasv = None

    def _vpath(self, arg: str) -> str:
        """Client path → normalized virtual path (.. cannot escape /)."""
        return _join(self.cwd, arg)

    def _fpath(self, arg: str) -> str:
        """Client path → filer path, confined under the gateway root."""
        v = self._vpath(arg)
        root = self.srv.root
        return v if root == "/" else (root + v).rstrip("/") or root

    def _need_auth(self) -> bool:
        if self.srv.users and self.authed_user is None:
            self.send(530, "Please login with USER and PASS.")
            return True
        return False

    # -- main loop -----------------------------------------------------------
    def run(self) -> None:
        try:
            self.send(220, "seaweedfs_tpu FTP gateway ready.")
            while True:
                # bounded: an unbounded readline() on a newline-free byte
                # stream would buffer the peer's entire output in memory
                raw = self._rfile.readline(_MAX_CMD_LINE)
                if not raw:
                    return
                if len(raw) >= _MAX_CMD_LINE and not raw.endswith(b"\n"):
                    self.send(500, "Command line too long.")
                    return  # framing is gone; drop the session
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                verb, _, arg = line.partition(" ")
                handler = getattr(self, f"do_{verb.upper()}", None)
                if handler is None:
                    self.send(502, f"Command {verb!r} not implemented.")
                    continue
                if verb.upper() not in (
                    "USER", "PASS", "QUIT", "SYST", "FEAT", "NOOP",
                ) and self._need_auth():
                    continue
                try:
                    if handler(arg):
                        return
                except Exception as e:  # noqa: BLE001 — keep session alive
                    glog.warning("ftp %s %s: %s", verb, arg, e)
                    self.send(451, "Action aborted: local error.")
        except OSError:
            pass
        finally:
            if self._pasv is not None:
                self._pasv.close()
            self.conn.close()

    # -- auth ----------------------------------------------------------------
    def do_USER(self, arg):
        self.pending_user = arg
        if not self.srv.users:
            self.authed_user = arg or "anonymous"
            self.send(230, "Login successful.")
        else:
            self.send(331, "Password required.")

    def do_PASS(self, arg):
        import hmac

        if not self.srv.users:
            self.authed_user = self.pending_user or "anonymous"
            self.send(230, "Login successful.")
            return
        # constant-time compare that runs for known AND unknown users
        # (ADVICE r2): unknown accounts compare against a dummy that can
        # never match, so timing doesn't enumerate accounts, and a user
        # legitimately configured with an empty password still logs in
        known = (self.pending_user or "") in self.srv.users
        expect = self.srv.users.get(self.pending_user or "")
        probe = expect if known else "\x00never-matches"
        ok = hmac.compare_digest(probe, arg or "")
        if known and ok:
            self.authed_user = self.pending_user
            self.send(230, "Login successful.")
        else:
            self.send(530, "Login incorrect.")

    # -- trivia --------------------------------------------------------------
    def do_SYST(self, arg):
        self.send(215, "UNIX Type: L8")

    def do_FEAT(self, arg):
        self.send_multi(211, ["Features:", " SIZE", " MDTM", " EPSV", "End"])

    def do_NOOP(self, arg):
        self.send(200, "OK.")

    def do_TYPE(self, arg):
        self.type = arg.upper() or "I"
        self.send(200, f"Type set to {self.type}.")

    def do_QUIT(self, arg):
        self.send(221, "Goodbye.")
        return True

    # -- navigation ----------------------------------------------------------
    def do_PWD(self, arg):
        self.send(257, f'"{self.cwd}" is the current directory.')

    def do_CWD(self, arg):
        virtual = self._vpath(arg)
        target = self._fpath(arg)
        e = self.srv.client.get_entry(target)
        if virtual == "/" or (e is not None and e.get("is_directory")):
            self.cwd = virtual
            self.send(250, "Directory changed.")
        else:
            self.send(550, "No such directory.")

    def do_CDUP(self, arg):
        return self.do_CWD("..")

    # -- passive data --------------------------------------------------------
    def do_PASV(self, arg):
        s = self._open_pasv()
        h = self.srv.host.replace(".", ",")
        port = s.getsockname()[1]
        self.send(227, f"Entering Passive Mode ({h},{port >> 8},{port & 0xFF}).")

    def do_EPSV(self, arg):
        s = self._open_pasv()
        self.send(229, f"Entering Extended Passive Mode (|||{s.getsockname()[1]}|)")

    # -- listings ------------------------------------------------------------
    def _entries(self, path: str) -> list[dict]:
        return list(self.srv.client.list(path, limit=10000))

    @staticmethod
    def _ls_line(e: dict) -> str:
        kind = "d" if e.get("is_directory") else "-"
        size = e.get("size", 0) or sum(
            c.get("size", 0) for c in e.get("chunks", [])
        )
        mtime = time.strftime(
            "%b %d %H:%M", time.localtime(e.get("mtime", 0) or 0)
        )
        return (
            f"{kind}rw-r--r-- 1 weed weed {size:>12} {mtime} {e['name']}"
        )

    def _send_listing(self, arg, names_only: bool):
        path = self._fpath(arg if arg and not arg.startswith("-") else ".")
        data = self._data_conn()
        if data is None:
            return
        self.send(150, "Here comes the directory listing.")
        try:
            entries = self._entries(path)
            if names_only:
                body = "".join(e["name"] + "\r\n" for e in entries)
            else:
                body = "".join(self._ls_line(e) + "\r\n" for e in entries)
            data.sendall(body.encode())
        finally:
            data.close()
        self.send(226, "Directory send OK.")

    def do_LIST(self, arg):
        self._send_listing(arg, names_only=False)

    def do_NLST(self, arg):
        self._send_listing(arg, names_only=True)

    # -- files ---------------------------------------------------------------
    def do_RETR(self, arg):
        path = self._fpath(arg)
        e = self.srv.client.get_entry(path)
        if e is None or e.get("is_directory"):
            # filer GET on a directory answers 200 with listing JSON —
            # never serve that as file bytes
            self.send(550, "Not a plain file.")
            return
        status, body, h = self.srv.client.get_object_stream(path)
        if status != 200:
            if hasattr(body, "close"):
                body.close()
            self.send(550, "File not found.")
            return
        data = self._data_conn()
        if data is None:
            body.close()
            return
        size = h.get("Content-Length", "?")
        self.send(150, f"Opening data connection for {arg} ({size} bytes).")
        sent = 0
        try:
            # piecewise relay: downloads of any size in bounded memory
            while True:
                piece = body.read(1 << 20)
                if not piece:
                    break
                data.sendall(piece)
                sent += len(piece)
        finally:
            body.close()
            data.close()
        if size != "?" and sent != int(size):
            # a premature upstream close surfaces as EOF on read(), not an
            # exception — a truncated transfer must never be acked as 226
            self.send(451, f"Transfer aborted: got {sent} of {size} bytes.")
            return
        self.send(226, "Transfer complete.")

    def _store(self, arg, append: bool):
        path = self._fpath(arg)
        data = self._data_conn()
        if data is None:
            return
        self.send(150, "Ok to send data.")
        # FTP sends until data-socket EOF (no length up front), so spool to
        # a size-capped temp file — big uploads ride the disk, then stream
        # to the filer with a known length (bounded gateway memory)
        import tempfile

        spool = tempfile.SpooledTemporaryFile(max_size=8 * 1024 * 1024)
        try:
            if append:
                # the existing object flows into the spool in bounded
                # pieces — appending to a multi-GB file must not buffer it
                status, old, oh = self.srv.client.get_object_stream(path)
                if status == 200:
                    try:
                        shutil.copyfileobj(old, spool, 1 << 20)
                    finally:
                        old.close()
                    want = oh.get("Content-Length")
                    if want is not None and spool.tell() != int(want):
                        # upstream died mid-read: EOF, not an exception —
                        # storing the truncated prefix would be silent
                        # data loss behind a 226
                        data.close()
                        self.send(451, "Append aborted: source read truncated.")
                        return
            try:
                while True:
                    buf = data.recv(65536)
                    if not buf:
                        break
                    spool.write(buf)
            finally:
                data.close()
            size = spool.tell()
            spool.seek(0)
            self.srv.client.put_object_stream(path, spool, size)
        finally:
            spool.close()
        self.send(226, "Transfer complete.")

    def do_STOR(self, arg):
        self._store(arg, append=False)

    def do_APPE(self, arg):
        self._store(arg, append=True)

    def do_DELE(self, arg):
        path = self._fpath(arg)
        e = self.srv.client.get_entry(path)
        if e is None or e.get("is_directory"):
            self.send(550, "File not found.")  # RMD is for directories
            return
        status = self.srv.client.delete(path)
        if status >= 300:
            self.send(550, f"Delete failed ({status}).")
        else:
            self.send(250, "File deleted.")

    def do_MKD(self, arg):
        path = self._fpath(arg)
        self.srv.client.mkdir(path)
        self.send(257, f'"{arg}" created.')

    def do_RMD(self, arg):
        path = self._fpath(arg)
        e = self.srv.client.get_entry(path)
        if e is None or not e.get("is_directory"):
            self.send(550, "No such directory.")
            return
        self.srv.client.delete(path, recursive=True)
        self.send(250, "Directory removed.")

    def do_SIZE(self, arg):
        e = self.srv.client.get_entry(self._fpath(arg))
        if e is None or e.get("is_directory"):
            self.send(550, "Not a file.")
            return
        size = sum(c.get("size", 0) for c in e.get("chunks", []))
        self.send(213, str(size))

    def do_MDTM(self, arg):
        e = self.srv.client.get_entry(self._fpath(arg))
        if e is None:
            self.send(550, "Not found.")
            return
        self.send(
            213, time.strftime("%Y%m%d%H%M%S", time.gmtime(e.get("mtime", 0)))
        )

    def do_RNFR(self, arg):
        path = self._fpath(arg)
        if self.srv.client.get_entry(path) is None:
            self.send(550, "Not found.")
            return
        self.rename_from = path
        self.send(350, "Ready for RNTO.")

    def do_RNTO(self, arg):
        if self.rename_from is None:
            self.send(503, "RNFR required first.")
            return
        src, self.rename_from = self.rename_from, None
        dst = self._fpath(arg)
        # the filer has an atomic server-side rename (?mv.to=) that moves
        # files and whole directories without copying bytes
        self.srv.client.rename(src, dst)
        if self.srv.client.get_entry(dst) is None:
            self.send(550, "Rename failed.")
        else:
            self.send(250, "Rename successful.")


class FtpServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8021,
        filer_url: str = "127.0.0.1:8888",
        root: str = "/",
        users: Optional[dict[str, str]] = None,
    ):
        self.host, self.port = host, port
        self.client = FilerClient(filer_url)
        self.root = root.rstrip("/") or "/"
        self.users = users or {}  # empty → anonymous access
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "FtpServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        self.port = s.getsockname()[1]
        s.listen(16)
        self._srv = s

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = s.accept()
                except OSError:
                    return
                _Session(self, conn, addr).start()

        threading.Thread(target=loop, daemon=True).start()
        glog.info("ftp gateway on %s:%d → filer", self.host, self.port)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            self._srv.close()
