"""Daemons: master server + volume server over threaded HTTP.

Transport note: the reference exposes assign/lookup and the whole data plane
over HTTP+JSON (`weed/server/master_server_handlers.go`,
`volume_server_handlers_*.go`) and uses gRPC streams for heartbeat/admin
(`pb/master.proto`, `pb/volume_server.proto`). Here every surface is HTTP:
the heartbeat stream becomes a periodic POST (same reconciliation semantics,
delta beats included), and the admin RPCs are POST endpoints mirroring the
gRPC method names so the parity mapping stays 1:1.
"""
